"""The Trainer — L5 of the layer map (SURVEY.md §1).

Same API shape as the reference's `Trainer` class (`__init__ / _run_batch /
_run_epoch / train`, reference ddp_gpus.py:25-55), rebuilt around one jitted
SPMD train step:

  * the hot loop `zero_grad → forward → loss → backward → step`
    (reference ddp_gpus.py:37-42) is a single `jax.jit`-compiled function of
    (state, batch) → (state, metrics) with donated state;
  * DDP's bucketed-Reducer gradient allreduce (reference ddp_gpus.py:35) is
    implicit: the batch is sharded over the data axes, so XLA emits and
    overlaps the gradient psum itself;
  * FSDP is the same step with parameter shardings from
    `fsdp_param_shardings` — XLA inserts all-gather/reduce-scatter;
  * `sampler.set_epoch` reshuffling (reference ddp_gpus.py:47) is driven by
    `fit`.
"""

from __future__ import annotations

import contextlib
import json
import os
import signal
import threading
import time
from functools import partial
from pathlib import Path
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorchdistributed_tpu.data.loader import prefetch_to_device
from pytorchdistributed_tpu.faults import inject as _faults_inject
from pytorchdistributed_tpu.faults.inject import EXIT_PREEMPTED
from pytorchdistributed_tpu.parallel.precision import Policy
from pytorchdistributed_tpu.parallel.sharding import shardings_for_strategy
from pytorchdistributed_tpu.runtime import dist
from pytorchdistributed_tpu.runtime.heartbeat import Heartbeat
from pytorchdistributed_tpu.data.loader import shard_batch
from pytorchdistributed_tpu.runtime.mesh import batch_leaf_sharding, create_mesh
from pytorchdistributed_tpu.telemetry import (
    TELEMETRY_DIR_ENV,
    AnomalyDetector,
    EventLog,
    SpanTracer,
    device_memory_highwater,
)
from pytorchdistributed_tpu.telemetry.diagnostics import (
    DiagnosticsConfig,
    split_scalars_tables,
)
from pytorchdistributed_tpu.telemetry.diagnostics import (
    DIAG_FILE as DIAGNOSTICS_FILE,
)
from pytorchdistributed_tpu.telemetry.events import (
    EVENT_PREEMPTED,
    EVENTS_FILE,
    METRICS_FILE,
)
from pytorchdistributed_tpu.telemetry.spans import SPAN_TRACE_FILE
from pytorchdistributed_tpu.training.logging import JsonlWriter, MetricLogger
from pytorchdistributed_tpu.utils.guards import (
    NaNWatchdog,
    assert_replicas_consistent,
)
from pytorchdistributed_tpu.utils.metrics import ThroughputMeter


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any


# BN running-statistics EMA momentum (torch BatchNorm's default). The fold
# lives here, not in models/resnet.SyncBatchNorm: the modules publish raw
# batch stats and the Trainer EMAs the whole "batch_stats" subtree in one
# pass — see _split_stats.
BN_EMA_MOMENTUM = 0.9

# Default XLA compile options for the jitted steps on TPU. The TPU
# compiler stages custom-call output tuples in its scoped-VMEM stack with
# a per-element eligibility check but a whole-tuple, TILE-PADDED frame
# allocation: the flash dKV backward's (dk, dv) tuple at head_dim 64
# lane-pads 2x (64 → 128 lanes), so a long-sequence train step aborts
# compilation at the default 16 MiB limit — measured v5e, Llama-1B at
# S=4096: "Scoped allocation with size 17.38M and limit 16.00M exceeded
# scoped vmem limit" (2026-07-31; chunking the kernel call does NOT help —
# the chunks' staged outputs are concurrently live, so the frame total is
# unchanged). 24 MiB clears the padded frame with room to spare and is
# far under physical VMEM on v4+ (~128 MiB on v5e).
_TPU_COMPILER_OPTIONS = {"xla_tpu_scoped_vmem_limit_kib": "24576"}

# Latency-hiding scheduler wiring (ISSUE 5b): make XLA start collectives
# asynchronously and schedule independent compute inside the
# start→done window — the DDP bucketed-Reducer overlap, as compiler
# scheduling. Concretely: the gradient all-reduce/reduce-scatter of
# EARLY layers can issue while later layers' backward still runs (dp/
# fsdp), and the TP activation collectives overlap the surrounding
# matmuls. This is the "xla" half of the overlap knob; the "ring" half
# (ops/overlap.py) decomposes the TP matmuls by hand on top of it.
# TPU-only (the CPU sim's collectives are synchronous rendezvous — these
# options are no-ops-at-best there, and the compiled-invariant pins must
# not move); verified via utils.hlo.overlap_census on the compiled HLO
# (async start/done pairing + ops scheduled between).
_TPU_OVERLAP_COMPILER_OPTIONS = {
    "xla_tpu_enable_latency_hiding_scheduler": "true",
    "xla_tpu_overlap_compute_collective_tc": "true",
    "xla_enable_async_all_gather": "true",
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
    "xla_tpu_enable_async_collective_fusion_multiple_steps": "true",
}


def _overlap_compiler_options(overlap: str) -> dict[str, str]:
    """The scheduler-flag half of Trainer(overlap=...): "xla"/"ring" wire
    the latency-hiding scheduler on TPU; "off" (the measured monolithic
    baseline) and non-TPU backends add nothing."""
    import jax as _jax

    if overlap == "off" or _jax.default_backend() != "tpu":
        return {}
    return dict(_TPU_OVERLAP_COMPILER_OPTIONS)


def _default_compiler_options() -> dict[str, str] | None:
    """The raised scoped-VMEM default, gated on TPU GENERATION (ADVICE
    r5): v2/v3 cores have ~16 MiB physical VMEM, so a 24 MiB scoped limit
    exceeds the hardware and can itself break compilation — XLA's
    conservative 16 MiB default exists for exactly those chips. Only v4
    and later (device_kind "TPU v4" / "TPU v5 lite" / "TPU v5p" / "TPU
    v6e" ...) get the override; unparseable kinds stay on XLA defaults."""
    if jax.default_backend() != "tpu":
        return None
    import re

    # first integer in the kind string: "TPU v5 lite" -> 5, "TPU v4" -> 4,
    # and generation tokens without the 'v' ("TPU7x" -> 7) — failing open
    # on an unparseable kind would silently drop the long-sequence compile
    # fix on exactly the newest chips
    m = re.search(r"(\d+)", jax.devices()[0].device_kind)
    if m is None or int(m.group(1)) < 4:
        return None
    return dict(_TPU_COMPILER_OPTIONS)


def _split_stats(params):
    """(trainable, batch_stats-or-None). Normalization running statistics
    are BUFFERS (torch semantics), not trainable parameters: they carry no
    gradient, get no optimizer slots, and are updated by the EMA fold in
    the train step. Keeping them out of the optimizer tree removes the
    zero-grad AD outputs and dead momentum-slot updates the r3 step paid
    for on every one of ResNet-50's ~100 norm layers (VERDICT r3 weak #2:
    the 2.5% EMA regression). Checkpoint note: opt_state treedefs saved
    BEFORE this change (r3 and earlier) carried dead slots for the stats
    and will not restore into the stripped structure — re-save from a
    fresh run (no cross-round checkpoints exist; the format is otherwise
    unchanged)."""
    if isinstance(params, dict) and "batch_stats" in params:
        return ({k: v for k, v in params.items() if k != "batch_stats"},
                params["batch_stats"])
    return params, None


def default_batch_adapter(batch) -> tuple:
    """batch dict → the model's positional inputs. The default serves the
    built-in task shapes (regression "x", vision "image", LM "tokens");
    models with richer signatures (attention masks, segment ids) pass an
    explicit ``batch_adapter`` to the Trainer — the loss_fn they bring reads
    the same batch keys itself."""
    for key in ("x", "image", "tokens"):
        if key in batch:
            return (batch[key],)
    raise ValueError(
        f"cannot infer model inputs from batch keys {list(batch)}; pass "
        f"Trainer(batch_adapter=...) mapping the batch to model args")


class Trainer:
    """``Trainer(model, optimizer, loss_fn).fit(loader, max_epochs)``.

    ``strategy`` selects the parallelism the reference reaches via wrapper
    classes: "dp" (replicated params ≙ DDP) or "fsdp" (ZeRO-3 sharding).
    ``precision=Policy.bf16()`` is the amp→bf16 port; ``remat=True`` enables
    activation checkpointing (GPipe's "time for space",
    03_model_parallel.ipynb:637-643). ``compiler_options`` are per-step XLA
    compile options, merged OVER the TPU backend defaults
    (_TPU_COMPILER_OPTIONS — scoped-VMEM headroom for the flash backward
    at long sequence); override a default by setting its key explicitly.
    ``telemetry_dir`` (or the launcher's PTD_TELEMETRY_DIR) enables the
    unified telemetry subsystem: host-span tracing around the loop's
    phases, per-rank metric JSONL with MFU/comm-bytes from StepAccounting,
    and anomaly-tripwire events — read it all back with
    ``python -m pytorchdistributed_tpu.telemetry report <dir>``.
    ``diagnostics`` (or PTD_DIAGNOSTICS; "off" | "scalars" | "full[:N]")
    adds in-graph model health to the same compiled step — per-layer
    activation stats, grad-norm groups/tables, update/param ratio and
    NaN provenance (telemetry/diagnostics.py) — streamed to a per-rank
    diagnostics JSONL next to the metric log; off costs literally
    nothing (byte-identical HLO).
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable,
        *,
        mesh=None,
        strategy: str = "dp",
        precision: Policy | None = None,
        remat: bool = False,
        log_every: int = 10,
        checkpoint_dir: str | None = None,
        checkpoint_every_steps: int = 0,
        watchdog: bool = True,
        profile_dir: str | None = None,
        batch_adapter: Callable | None = None,
        accum_steps: int = 1,
        metrics_file: str | None = None,
        compiler_options: dict[str, str] | None = None,
        telemetry_dir: str | None = None,
        overlap: str = "xla",
        prefetch: int | None = None,
        diagnostics: str | DiagnosticsConfig | None = None,
        compile_cache="auto",
    ):
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else create_mesh()
        self.strategy = strategy
        self.precision = precision or Policy.full()
        self.remat = remat
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self.accum_steps = accum_steps
        # Collective-overlap mode (ISSUE 5): "xla"/"ring" wire the TPU
        # latency-hiding scheduler flags into the step's compile options
        # (the model-side ring routing is TransformerConfig.overlap);
        # "off" is the measured monolithic baseline.
        from pytorchdistributed_tpu.parallel.overlap import validate_overlap
        self.overlap = validate_overlap(overlap)
        # Device prefetch depth (per-batch H2D double-buffering): the
        # explicit arg wins, then the PTD_PREFETCH env contract, then the
        # loader default of 2. Depth 0 = fully synchronous transfer.
        if prefetch is None:
            prefetch = int(os.environ.get("PTD_PREFETCH", "2"))
        if prefetch < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {prefetch}")
        self.prefetch = prefetch
        # In-graph training diagnostics (ISSUE 6, telemetry/diagnostics.py):
        # explicit arg wins ("off" | "scalars" | "full[:N]"), then the
        # PTD_DIAGNOSTICS env contract, then off. On: the train step
        # additionally returns per-layer activation health, grad-norm
        # groups/tables, the update/param RMS ratio and the NaN-provenance
        # scalar — all as extra jitted OUTPUTS of the same compiled step
        # (zero extra dispatches). Off: not one op is added — the compiled
        # HLO is byte-identical (pinned in test_compiled_invariants.py).
        self._diag = DiagnosticsConfig.resolve(diagnostics)
        self._diag_writer = None
        self._pending_diag_tables: dict = {}
        self._diag_table_next = (self._diag.table_every
                                 if self._diag is not None else 0)
        # User options MERGE OVER the backend defaults — a caller tuning an
        # unrelated flag must not silently drop the scoped-VMEM fix (to
        # override a default, set its key explicitly, e.g.
        # {"xla_tpu_scoped_vmem_limit_kib": "16384"} restores the XLA
        # default and with it the S=4096 compile abort).
        defaults = _default_compiler_options() or {}
        defaults.update(_overlap_compiler_options(self.overlap))
        self._compiler_options = {**defaults, **(compiler_options or {})}
        if not self._compiler_options:
            self._compiler_options = None  # jit expects None, not {}
        self.log_every = log_every
        from pytorchdistributed_tpu.parallel.tp import logical_rules
        self._rules = logical_rules(strategy)
        self.checkpoint = None
        self._checkpoint_every = checkpoint_every_steps
        if checkpoint_dir is not None:
            from pytorchdistributed_tpu.training.checkpoint import (
                CheckpointManager,
            )
            self.checkpoint = CheckpointManager(
                checkpoint_dir,
                save_interval_steps=max(checkpoint_every_steps, 1))
        # metrics_file: rank-0 JSONL sink — per-step metrics as data
        # (SURVEY.md §5), one durable line per logged step
        self.logger = MetricLogger(
            jsonl_path=metrics_file if dist.is_main_process() else None)
        # Unified telemetry (telemetry/): span tracer + anomaly tripwires
        # + per-rank metric JSONL + StepAccounting, all keyed off one run
        # directory — the explicit arg, or the launcher's env contract
        # (run.py --telemetry-dir exports PTD_TELEMETRY_DIR so workers
        # opt in without code changes). Off (all None) when neither is
        # set: the hot loop then pays only a handful of `is None` checks.
        tdir = telemetry_dir or os.environ.get(TELEMETRY_DIR_ENV)
        self.telemetry_dir = Path(tdir) if tdir else None
        self._tracer = None
        self._events = None
        self._anomaly = None
        self._telemetry_jsonl = None
        self.accounting = None
        # process_index when jax.distributed is up; otherwise the
        # launcher env contract's RANK (a run.py worker that hasn't — or
        # won't — init the process group must still get distinct
        # per-rank telemetry files, not clobber rank 0's)
        self._telemetry_rank = (
            jax.process_index() if jax.process_count() > 1
            else int(os.environ.get("RANK", "0")))
        if self.telemetry_dir is not None:
            self.telemetry_dir.mkdir(parents=True, exist_ok=True)
            rank = self._telemetry_rank
            self._tracer = SpanTracer(rank=rank)
            self._events = EventLog(
                self.telemetry_dir / EVENTS_FILE.format(rank=rank),
                rank=rank)
            self._anomaly = AnomalyDetector()
            self._telemetry_jsonl = JsonlWriter(
                self.telemetry_dir / METRICS_FILE.format(rank=rank))
            if self._diag is not None:
                # per-rank diagnostics JSONL next to the metric log —
                # scalar rows at log cadence, per-layer tables at the
                # configured cadence (diagnostics.py DIAG_FILE contract)
                self._diag_writer = JsonlWriter(
                    self.telemetry_dir / DIAGNOSTICS_FILE.format(rank=rank))
        self._dispatch_shapes: set = set()
        self._accounting_attempted = False
        self._last_batch_samples = 0
        self._loss_fn = loss_fn
        self._batch_adapter = batch_adapter or default_batch_adapter
        self._steps_per_epoch: int | None = None
        # SURVEY.md §5 wiring: the watchdog checks metrics at log cadence
        # (a float() on a device value blocks on the step, so an every-step
        # check would serialize the hot loop and defeat prefetch overlap)
        # and the full param tree every `state_every` checks.
        self._watchdog = NaNWatchdog() if watchdog else None
        # Liveness beats for the elastic agent's hung-rank detection
        # (run.py --heartbeat-timeout); None outside a launcher that asked.
        # Beats fire where the host BLOCKS on device values (log cadence,
        # epoch end) — host-loop progress alone proves nothing under async
        # dispatch (see runtime/heartbeat.py).
        self._heartbeat = Heartbeat.from_env()
        # Deterministic fault injection (faults/inject.py): None unless
        # the PTD_FAULTS env spec is set (run.py --faults). The hot loop
        # pays one `is None` check per step when off.
        self._faults = _faults_inject.active()
        # Graceful-preemption state: fit() installs a SIGTERM handler
        # (main thread only) that flips this flag; the step loop then
        # finishes the in-flight step, forces a durable checkpoint and
        # exits EXIT_PREEMPTED — the contract run.py's agent recognizes
        # as restart-worthy but never rank-attributable.
        self._preempt_requested = False
        self._meter = ThroughputMeter()
        self.profile_dir = profile_dir
        self._profiling = False
        self.state: TrainState | None = None
        self.state_shardings = None
        self._step_fn = None
        self._eval_fn = None
        # Persistent AOT executable cache (ISSUE 10, runtime/
        # compile_cache.py): "auto" reads the PTD_COMPILE_CACHE env
        # contract (off when unset), a path/instance attaches one
        # explicitly. With a cache, the train-step executable is keyed
        # by the sha256 of its LOWERED StableHLO (tracing always runs —
        # it is what captures the loss closure, optimizer constants and
        # shardings — only the expensive XLA compile is skipped), so a
        # relaunched incarnation deserializes the step in seconds and
        # train_step dispatches through it with zero XLA compiles.
        # Never-fails: any cache/AOT defect falls back to the jit path.
        from pytorchdistributed_tpu.runtime.compile_cache import (
            CompileCache,
        )
        self._compile_cache = CompileCache.resolve(compile_cache)
        self._aot_steps: dict = {}     # batch signature -> Compiled
        self._aot_failed: set = set()
        # XLA:CPU's in-process collective rendezvous deadlocks when too many
        # multi-device programs sit in the async dispatch queue (observed at
        # ~100 queued 8-device all-reduce steps on the CPU sim). Real jobs
        # force device values at log cadence anyway; this backstop bounds
        # the queue for callers that loop train_step without ever reading a
        # metric. TPU is unaffected (0 = never force).
        self._force_every = (
            32 if jax.default_backend() == "cpu"
            and self.mesh.devices.size > 1 else 0)
        self._unforced = 0
        # Rank-aware per-leaf batch layout: leading dim over the data axes;
        # 2-D token leaves also over "seq" when the mesh has a
        # context-parallel axis (ring/ulysses attention read seq-sharded
        # activations inside shard_map).
        self.batch_sharding = lambda leaf: batch_leaf_sharding(
            self.mesh, getattr(leaf, "ndim", 0))

    # -- initialization ----------------------------------------------------

    def init(self, sample_batch, seed: int = 0) -> TrainState:
        """Create the (possibly sharded) TrainState without ever
        materializing unsharded params on one device."""

        def make_state(rng, batch):
            with nn.logical_axis_rules(self._rules):
                variables = self.model.init(rng, *self._model_args(batch))
            params = nn.meta.unbox(_drop_sown(variables))
            trainable, _ = _split_stats(params)
            opt_state = self.optimizer.init(trainable)
            return TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=opt_state,
            )

        rng = jax.random.key(seed)
        self._prepare_abstract(sample_batch, rng)
        with self._span("init_state"), jax.set_mesh(self.mesh):
            self.state = jax.jit(
                make_state, out_shardings=self.state_shardings,
                compiler_options=self._compiler_options,
            )(rng, sample_batch)
        self._step_fn = self._build_step()
        self._maybe_build_accounting(sample_batch)
        return self.state

    # -- telemetry ---------------------------------------------------------

    def _span(self, name: str):
        """A host span when telemetry is on, else a nullcontext — the
        single gate every instrumented region goes through."""
        if self._tracer is None:
            return contextlib.nullcontext()
        return self._tracer.span(name)

    def step_accounting(self, sample_batch):
        """`telemetry.StepAccounting` for THIS trainer's step at this
        batch shape: AOT-lower + compile (`lower_step`) and read the
        executable's cost analysis and collective-bytes census. With a
        compile cache attached (ISSUE 10) the executable is loaded
        through it — a restarted run deserializes instead of paying the
        extra compile, and the same executable then backs train_step's
        AOT dispatch, so accounting costs nothing marginal."""
        from pytorchdistributed_tpu.telemetry import StepAccounting

        if self._compile_cache is not None:
            compiled = self._load_or_compile_step(sample_batch)
        else:
            compiled = self.lower_step(sample_batch).compile()
        return StepAccounting.from_compiled(
            compiled, batch=sample_batch, n_devices=self.mesh.devices.size)

    def _maybe_build_accounting(self, sample_batch) -> None:
        """With telemetry on, build StepAccounting once and stamp it into
        the run dir (rank 0). Failure is non-fatal AND one-shot: a
        backend where the build raises must pay the attempt (an AOT
        compile) once, not once per step — accounting is derived
        observability and must never drag down the job it observes."""
        if (self.telemetry_dir is None or self.accounting is not None
                or self._accounting_attempted):
            return
        self._accounting_attempted = True
        try:
            with self._span("step_accounting"):
                self.accounting = self.step_accounting(sample_batch)
            if dist.is_main_process():
                self.accounting.save(self.telemetry_dir / "accounting.json")
        except Exception as e:  # pragma: no cover - depends on backend
            self.logger.info(f"telemetry: step accounting unavailable ({e})")

    def _teardown_telemetry(self) -> None:
        """Epoch-boundary (and exception-path) durability: flush/close
        every telemetry sink and dump the span trace. Everything here
        reopens lazily, so multi-epoch fits keep appending."""
        if self.telemetry_dir is None:
            return
        self._tracer.dump(
            self.telemetry_dir
            / SPAN_TRACE_FILE.format(rank=self._telemetry_rank))
        self._events.close()
        self._telemetry_jsonl.close()
        if self._diag_writer is not None:
            self._diag_writer.close()

    def lower_step(self, sample_batch, seed: int = 0):
        """AOT-lower the jitted train step from ABSTRACT state: no params
        are materialized and no device computation runs — only tracing.
        Returns the `jax.stages.Lowered`; `.compile()` on it yields the
        exact executable `train_step` would run for this (config, mesh,
        strategy, batch shape), whose optimized HLO / memory analysis the
        compiled-invariant tripwires assert against committed numbers
        (tests/test_compiled_invariants.py) — the hardware-independent
        stand-in for the reference's benchmark-as-test discipline
        (03_model_parallel.ipynb:403-423) when no chip is reachable."""
        state_sds, batch_sds = self._step_sds(sample_batch, seed)
        step_fn = self._build_step()
        with jax.set_mesh(self.mesh):
            return step_fn.lower(state_sds, batch_sds)

    def _step_sds(self, sample_batch, seed: int = 0):
        """(state, batch) ShapeDtypeStruct trees with their shardings —
        the train step's exact AOT calling convention, shared by
        lower_step and the compile-cache key."""
        abstract = self._prepare_abstract(sample_batch, jax.random.key(seed))
        state_sds = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            abstract, self.state_shardings)
        batch_sds = jax.tree.map(
            lambda v: jax.ShapeDtypeStruct(
                v.shape, v.dtype, sharding=self.batch_sharding(v)),
            dict(sample_batch))
        return state_sds, batch_sds

    def _load_or_compile_step(self, sample_batch):
        """The train-step executable through the persistent cache:
        trace + lower always run (cheap, and the lowered StableHLO hash
        is the part of the cache key that captures everything the
        closure bakes in — loss fn, optimizer hyperparams, precision
        casts), then the XLA compile is either skipped (deserialize a
        committed entry) or paid once and published. Memoized per batch
        signature; shared by step_accounting and the train_step AOT
        dispatch path."""
        sig = self._batch_signature(sample_batch)
        compiled = self._aot_steps.get(sig)
        if compiled is not None:
            return compiled
        import hashlib

        state_sds, batch_sds = self._step_sds(sample_batch)
        # reuse the live jit wrapper when one exists: its tracing cache
        # makes this lower() free on the train_step hot path
        step_fn = (self._step_fn if self._step_fn is not None
                   else self._build_step())
        with jax.set_mesh(self.mesh):
            lowered = step_fn.lower(state_sds, batch_sds)
        hlo_hash = hashlib.sha256(
            lowered.as_text().encode()).hexdigest()
        compiled, _ = self._compile_cache.load_or_compile(
            "train_step", lowered.compile, (state_sds, batch_sds),
            statics=(f"strategy={self.strategy};"
                     f"accum={self.accum_steps};overlap={self.overlap};"
                     f"opts={self._compiler_options!r}"),
            config_hash=hlo_hash, donation="state")
        self._aot_steps[sig] = compiled
        return compiled

    @staticmethod
    def _batch_signature(batch):
        return tuple(sorted(
            (k, tuple(getattr(v, "shape", ())),
             str(getattr(v, "dtype", "")))
            for k, v in dict(batch).items()))

    def _prepare_abstract(self, sample_batch, rng) -> "TrainState":
        """Abstract TrainState + self.state_shardings, with NO device work:
        shared by init() (which then materializes) and restore() (which
        loads a checkpoint straight into the shardings)."""
        # Boxed abstract init: the Partitioned leaves carry the logical axis
        # names the sharding rules consume. The full abstract state is
        # derived from it (unbox + abstract optimizer init) rather than
        # re-tracing the model. Traced under the mesh context: sharded
        # attention (ring/ulysses shard_map) needs the ambient mesh even
        # abstractly.
        with jax.set_mesh(self.mesh):
            abstract_boxed = jax.eval_shape(
                lambda r, b: self.model.init(r, *self._model_args(b)),
                rng, sample_batch,
            )
        abstract_boxed = _drop_sown(abstract_boxed)
        abstract_params = nn.meta.unbox(abstract_boxed)
        abstract_trainable, _ = _split_stats(abstract_params)
        abstract = TrainState(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            params=abstract_params,
            opt_state=jax.eval_shape(self.optimizer.init,
                                     abstract_trainable),
        )
        # Collective-mismatch guard (SURVEY.md §5) BEFORE the first compile:
        # divergent structure across processes deadlocks the pod the way
        # mismatched NCCL calls do; the digest allgather fails fast instead.
        assert_replicas_consistent(abstract, name="abstract TrainState")
        param_sh = shardings_for_strategy(
            self.strategy, abstract_boxed, self.mesh
        )
        trainable_sh, _ = _split_stats(param_sh)
        self.state_shardings = TrainState(
            step=NamedSharding(self.mesh, P()),
            params=param_sh,
            opt_state=_opt_state_shardings(
                abstract.opt_state, abstract_trainable, trainable_sh,
                self.mesh
            ),
        )
        return abstract

    def _model_args(self, batch):
        return self._batch_adapter(batch)

    # -- the jitted hot loop ----------------------------------------------

    def _transformer_cfg(self):
        """The model's TransformerConfig, unwrapping containers that nest it
        (ViTConfig.transformer)."""
        cfg = getattr(self.model, "cfg", None)
        return getattr(cfg, "transformer", cfg)

    def _build_step(self):
        cfg = self._transformer_cfg()
        if (getattr(cfg, "pipeline_stages", 1) > 1
                and getattr(cfg, "pp_schedule", "gpipe") == "1f1b"):
            if self.accum_steps > 1:
                # 1F1B already splits the batch into pipeline_microbatches
                # inside its fused schedule — raise rather than silently
                # ignore the flag (scale pipeline_microbatches instead).
                raise ValueError(
                    "accum_steps > 1 does not compose with "
                    "pp_schedule='1f1b'; raise pipeline_microbatches "
                    "instead (the fused schedule is already micro-batched)")
            return self._build_1f1b_step()
        policy = self.precision
        loss_fn = self._loss_fn
        diag = self._diag
        diag_layers = getattr(cfg, "num_layers", None)
        if diag is not None:
            # activation-health collection rides the loss only when the
            # loss advertises the kwarg (all built-ins do); a custom loss
            # without it still gets grad/update health — the trainer-side
            # half needs nothing from the loss
            import inspect

            if "diagnostics" in inspect.signature(loss_fn).parameters:
                loss_fn = partial(loss_fn, diagnostics=True)
            elif dist.is_main_process():
                self.logger.info(
                    "diagnostics: loss_fn "
                    f"{getattr(self._loss_fn, '__name__', self._loss_fn)!r} "
                    "takes no diagnostics= kwarg — per-layer activation "
                    "stats are off; grad/update health still reports")
        if self.remat:
            loss_fn = jax.checkpoint(loss_fn, static_argnums=(0,))

        accum = self.accum_steps

        def step(state: TrainState, batch):
            # Derive the per-step rng on device from state.step — a host-side
            # int(state.step) here would block on the previous step and
            # serialize the hot loop, defeating the prefetcher's overlap.
            rng = jax.random.fold_in(jax.random.key(1_234_567), state.step)
            # Buffers out of the differentiated/optimized tree: grads, the
            # optimizer update and apply_updates all run over `trainable`
            # only; `stats` re-enters via the loss closure (the model still
            # reads the EMA) and is EMA-folded once at the end.
            trainable, stats = _split_stats(state.params)

            def compute_loss(tparams, mb, mb_rng):
                full = (tparams if stats is None
                        else {**tparams, "batch_stats": stats})
                cparams = policy.cast_params_for_compute(full)
                cbatch = policy.cast_batch(mb)
                with nn.logical_axis_rules(self._rules):
                    loss, metrics = loss_fn(self.model, cparams, cbatch,
                                            mb_rng)
                return loss.astype(jnp.float32), metrics

            diag_acts = None
            if accum == 1:
                (_, metrics), grads = jax.value_and_grad(
                    compute_loss, has_aux=True
                )(trainable, batch, rng)
                diag_acts = metrics.pop("_diag_acts", None)
            else:
                # Gradient accumulation: lax.scan over accum micro-batches
                # INSIDE the jitted step (one compiled program, activations
                # for one micro-batch alive at a time), fp32-accumulated
                # grads normalized once before the single optimizer update —
                # the large-batch recipe when the full batch's activations
                # exceed HBM. Masked losses (MLM loss_mask) are EXACT
                # (closes ADVICE r2): each micro-batch reports its token
                # count ("_mask_count"), its grads are weighted by it, and
                # one global normalization follows — since each loss_i is
                # ce_sum_i/count_i, Σ count_i·∇loss_i / Σ count_i =
                # ∇(Σ ce_sum / Σ count), the full-batch masked mean. Same
                # global-normalization trick as PipelineParts.targets_of on
                # the 1F1B path. (The MoE aux term's grads ride the same
                # weights — per-token weighting of a heuristic
                # load-balance objective, a definition, not an error.)
                def as_microbatches(leaf):
                    b = leaf.shape[0]
                    if b % accum:
                        raise ValueError(
                            f"global batch {b} not divisible by "
                            f"accum_steps {accum}")
                    return leaf.reshape(accum, b // accum, *leaf.shape[1:])

                mbs = jax.tree.map(as_microbatches, batch)

                def body(carry, mb_i):
                    g_acc, c_acc = carry
                    mb, i = mb_i
                    (_, metrics), g = jax.value_and_grad(
                        compute_loss, has_aux=True
                    )(trainable, mb, jax.random.fold_in(rng, i))
                    w = metrics.get("_mask_count")
                    wi = jnp.float32(1.0) if w is None else w
                    g_acc = jax.tree.map(
                        lambda a, b: a + wi * b.astype(jnp.float32), g_acc, g)
                    return (g_acc, c_acc + wi), metrics

                g0 = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), trainable)
                (grads, c_acc), metrics = jax.lax.scan(
                    body, (g0, jnp.float32(0.0)), (mbs, jnp.arange(accum)))
                c_acc = jnp.maximum(c_acc, 1.0)  # all-masked-out batch
                grads = jax.tree.map(lambda g: g / c_acc, grads)
                # activation-health tables out BEFORE the metric
                # reduction: they are [accum, L]-stacked here, and the
                # token-weighted branch below broadcasts against scalar
                # metrics only; a plain mean over micro-batches is the
                # right reduction for diagnostic stats either way
                diag_acts = metrics.pop("_diag_acts", None)
                if diag_acts is not None:
                    diag_acts = jax.tree.map(lambda a: a.mean(0), diag_acts)
                wts = metrics.pop("_mask_count", None)
                if wts is None:
                    # plain mean over micro-batches; for "_collections"
                    # (raw batch stats) the mean of per-micro-batch means
                    # IS the full-batch mean (vars: within-micro-batch
                    # only, the same approximation the per-module EMA made)
                    metrics = jax.tree.map(lambda m: m.mean(0), metrics)
                else:
                    # token-count-weighted mean == the full-batch masked
                    # mean (masked losses carry scalar metrics only, so
                    # no "_collections" leaf rides this branch)
                    metrics = jax.tree.map(
                        lambda m: (m * wts).sum(0) / c_acc, metrics)
            # Mutable-collection updates (ResNet's raw batch stats) ride
            # the metrics; they are STATE, not a scalar — EMA-fold them
            # into the buffer subtree in one tree pass (see _split_stats;
            # no optimizer involvement, matching torch buffer semantics).
            new_colls = metrics.pop("_collections", None)
            # Grads arrive in compute dtype; master update stays fp32.
            grads = jax.tree.map(
                lambda g, p: g.astype(p.dtype), grads, trainable
            )
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, trainable
            )
            params = optax.apply_updates(trainable, updates)
            if diag is not None:
                # in-graph optimizer + activation health (ISSUE 6): a few
                # reductions over trees the step already holds, folded
                # into the SAME metrics pytree — dispatch count unchanged
                from pytorchdistributed_tpu.telemetry.diagnostics import (
                    diagnostics_metrics,
                )

                metrics.update(diagnostics_metrics(
                    acts=diag_acts, grads=grads, params=trainable,
                    updates=updates, num_layers=diag_layers))
            if new_colls is not None:
                new_colls = dict(new_colls)
                new_stats = new_colls.pop("batch_stats", None)
                # non-stat mutable collections keep the old overwrite
                # semantics (none exist today; "losses" is dropped at init)
                params = {**params, **new_colls}
                if new_stats is not None and stats is not None:
                    m = BN_EMA_MOMENTUM
                    stats = jax.tree.map(
                        lambda old, new: m * old + (1 - m) * new,
                        stats, new_stats)
            if stats is not None:
                params = {**params, "batch_stats": stats}
            new_state = TrainState(
                step=state.step + 1, params=params, opt_state=opt_state
            )
            # underscore keys are loss→trainer plumbing (_mask_count), not
            # reportable metrics
            metrics = {k: v.astype(jnp.float32) for k, v in metrics.items()
                       if not k.startswith("_")}
            return new_state, metrics

        return jax.jit(
            step,
            in_shardings=(self.state_shardings, None),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
            compiler_options=self._compiler_options,
        )

    def _build_1f1b_step(self):
        """Fused 1F1B pipeline train step (pp_schedule="1f1b").

        1F1B interleaves each micro-batch's backward between later
        micro-batches' forwards, so it cannot be expressed as a forward pass
        plus AD — the whole step (forward + loss + backward) is one schedule
        (parallel/pipeline.py `one_f_one_b`). The model supplies its
        pre/stages/head decomposition via ``pipeline_parts()``; only the
        pre-stage part (embeddings) is differentiated by AD, seeded with the
        ``dx`` cotangent the pipeline returns. The optimizer update is
        identical to the AD path's."""
        from pytorchdistributed_tpu.parallel.pipeline import one_f_one_b

        if not hasattr(self.model, "pipeline_parts"):
            raise ValueError(
                f"pp_schedule='1f1b' needs {type(self.model).__name__}"
                f".pipeline_parts() (the pre/stages/head decomposition); "
                f"use pp_schedule='gpipe' for models without one")
        cfg = self._transformer_cfg()
        from pytorchdistributed_tpu.training.losses import (
            MOE_AUX_WEIGHT,
            cross_entropy_loss,
            fused_token_cross_entropy_loss,
            moe_token_cross_entropy_loss,
            token_cross_entropy_loss,
        )
        if self._loss_fn not in (token_cross_entropy_loss,
                                 fused_token_cross_entropy_loss,
                                 moe_token_cross_entropy_loss,
                                 cross_entropy_loss):
            # The fused step computes loss inside the pipeline's last stage
            # (model.pipeline_parts().head_loss) — the Trainer-level loss_fn
            # cannot be threaded through it. Raise rather than warn: a user
            # who passed a custom objective would otherwise train a
            # different one.
            raise ValueError(
                f"pp_schedule='1f1b' computes its loss inside the pipeline "
                f"(model.pipeline_parts().head_loss); the custom loss_fn "
                f"{getattr(self._loss_fn, '__name__', self._loss_fn)!r} "
                f"cannot be threaded through the fused schedule — use the "
                f"built-in token CE losses or pp_schedule='gpipe'")
        parts = self.model.pipeline_parts()
        if self._diag is not None and dist.is_main_process():
            # the fused schedule runs the blocks via raw block.apply
            # inside a shard_map — the sown diagnostics collection cannot
            # ride it (same reason the loss must be built in)
            self.logger.info(
                "diagnostics: pp_schedule='1f1b' runs the fused pipeline "
                "step — in-graph diagnostics are not collected there "
                "(use gpipe or a non-pipeline strategy to profile health)")
        if self._loss_fn is cross_entropy_loss and dist.is_main_process():
            # the fused head computes loss only — the sequential path's
            # extra metrics (accuracy) don't ride the pipeline
            self.logger.info(
                "pp_schedule='1f1b' reports {'loss'} only; accuracy and "
                "other auxiliary metrics are not computed inside the fused "
                "pipeline (use evaluate() for them)")
        policy = self.precision
        use_aux = getattr(cfg, "moe_experts", 0) > 0
        if use_aux and parts.stage_apply_aux is None:
            raise ValueError(
                f"moe_experts > 0 with pp_schedule='1f1b' needs "
                f"{type(self.model).__name__}.pipeline_parts() to provide "
                f"stage_apply_aux (the Switch aux loss must ride the fused "
                f"pipeline)")
        stage_fn = parts.stage_apply_aux if use_aux else parts.stage_apply
        # loss convention matches moe_token_cross_entropy_loss: ce +
        # MOE_AUX_WEIGHT · mean-over-layers(aux); stage_apply_aux sums over
        # layers, so fold the 1/L in here.
        aux_weight = MOE_AUX_WEIGHT / cfg.num_layers if use_aux else 0.0
        train_dropout = cfg.dropout_rate > 0

        def step(state: TrainState, batch):
            cparams = policy.cast_params_for_compute(state.params)
            targets = (parts.targets_of(batch) if parts.targets_of
                       else batch["targets"])
            dropout_rng = (
                jax.random.fold_in(jax.random.key(1_234_567), state.step)
                if train_dropout else None)
            with nn.logical_axis_rules(self._rules):
                pre_p, stage_p, head_p = parts.split(cparams)
                x, pre_vjp = jax.vjp(
                    lambda pp: parts.pre_apply(pp, *self._model_args(batch)),
                    pre_p)
                loss, stage_g, head_g, dx = one_f_one_b(
                    stage_fn, stage_p, parts.head_loss, head_p,
                    x, targets,
                    num_microbatches=cfg.pipeline_microbatches,
                    mesh=self.mesh, dropout_rng=dropout_rng,
                    aux_weight=aux_weight)
                (pre_g,) = pre_vjp(dx)
                grads = parts.merge_grads(pre_g, stage_g, head_g)
            # opt_state is built over the buffer-stripped tree (see
            # _split_stats); the fused pipeline never refreshes stats, so
            # they re-enter unchanged. (No pipeline model carries
            # batch_stats today — this keeps the trees aligned if one does.)
            trainable, stats = _split_stats(state.params)
            grads, _ = _split_stats(grads)
            grads = jax.tree.map(
                lambda g, p: g.astype(p.dtype), grads, trainable)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, trainable)
            params = optax.apply_updates(trainable, updates)
            if stats is not None:
                params = {**params, "batch_stats": stats}
            new_state = TrainState(
                step=state.step + 1, params=params, opt_state=opt_state)
            return new_state, {"loss": loss.astype(jnp.float32)}

        return jax.jit(
            step,
            in_shardings=(self.state_shardings, None),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
            compiler_options=self._compiler_options,
        )

    def train_step(self, batch) -> dict[str, float]:
        """One optimizer step (the reference's ``_run_batch``)."""
        if self.state is None:
            self.init(batch)
        if self._step_fn is None:  # state came from restore(), not init()
            self._step_fn = self._build_step()
        if any(not isinstance(v, jax.Array) for v in batch.values()):
            with self._span("h2d_transfer"):
                batch = shard_batch(batch, self.batch_sharding)
        # AOT dispatch (ISSUE 10): with a compile cache, resolve this
        # batch signature to a persistent-cache executable once — a
        # relaunched incarnation deserializes the step instead of
        # compiling it — and dispatch through it. Any failure (a
        # backend that cannot serialize, a sharding the baked
        # convention rejects) permanently falls this signature back to
        # the jit path: the cache can only ever make restart faster.
        step_fn = self._step_fn
        if self._compile_cache is not None:
            sig = self._batch_signature(batch)
            if sig not in self._aot_steps and sig not in self._aot_failed:
                try:
                    with self._span("aot_load_or_compile"):
                        self._load_or_compile_step(batch)
                except Exception as e:  # noqa: BLE001 — never-fails
                    self._aot_failed.add(sig)
                    if dist.is_main_process():
                        self.logger.info(
                            f"compile cache: AOT step unavailable for "
                            f"this batch shape ({e}); using the jit "
                            f"path")
            step_fn = self._aot_steps.get(sig, self._step_fn)
        # a dispatch of a batch-shape signature not seen before carries
        # an XLA (re)compile — name it so host traces separate compile
        # stalls from steady-state dispatch (e.g. a ragged final batch
        # recompiling mid-epoch); the key is only built when tracing
        name = "step_dispatch"
        if self._tracer is not None:
            key = tuple(sorted(
                (k, tuple(getattr(v, "shape", ()))) for k, v in
                batch.items()))
            if key not in self._dispatch_shapes:
                self._dispatch_shapes.add(key)
                name = "compile_and_dispatch"
        try:
            with self._span(name), jax.set_mesh(self.mesh):
                self.state, metrics = step_fn(self.state, batch)
        except Exception as e:
            if step_fn is self._step_fn:
                raise
            self._aot_steps.pop(sig, None)
            self._aot_failed.add(sig)
            self._compile_cache.note_exec_failure("train_step", e)
            # a call REJECTED before execution leaves the donated state
            # intact for the jit retry; a mid-execution failure has
            # already consumed it — re-raise the real error instead of
            # masking it with the retry's "Array has been deleted"
            if any(getattr(a, "is_deleted", lambda: False)()
                   for a in jax.tree_util.tree_leaves(self.state)):
                raise
            with self._span(name), jax.set_mesh(self.mesh):
                self.state, metrics = self._step_fn(self.state, batch)
        if self._diag is not None:
            # route the per-layer [L] tables out of the scalar metric
            # stream on the host (pure dict work — the device arrays are
            # NOT forced here; they sync only if/when a table row is due)
            _, tables = split_scalars_tables(metrics)
            if tables:
                self._pending_diag_tables = tables
                metrics = {k: v for k, v in metrics.items()
                           if k not in tables}
        self._bound_dispatch_queue(metrics)
        return metrics

    def _bound_dispatch_queue(self, metrics) -> None:
        """See _force_every: every multi-device dispatch on the CPU sim
        counts against the queue bound, train and eval alike."""
        if self._force_every:
            self._unforced += 1
            if self._unforced >= self._force_every:
                jax.block_until_ready(metrics)
                self._unforced = 0

    # -- epochs ------------------------------------------------------------

    def run_epoch(self, loader, epoch: int, *,
                  skip_steps: int = 0) -> dict[str, float]:
        """The reference's ``_run_epoch`` (ddp_gpus.py:44-51), without its
        extra-batch-fetch wart (SURVEY.md §3.1). ``skip_steps`` fast-forwards
        past batches a resumed mid-epoch checkpoint already trained on."""
        loader.set_epoch(epoch)
        self._steps_per_epoch = len(loader)
        if dist.is_main_process():
            self.logger.info(
                f"epoch {epoch} | steps {len(loader)} | "
                f"per-process batch {loader.batch_size}"
            )
        metrics = {}
        raw = iter(loader)
        for _ in range(skip_steps):  # already trained before the restart
            next(raw, None)
        if self._tracer is not None:
            raw = self._spanned_iter(raw)
        it = prefetch_to_device(raw, self.batch_sharding,
                                size=self.prefetch, tracer=self._tracer)
        try:
            for i, batch in enumerate(it, start=skip_steps):
                if self.state is None:
                    self.init(batch)
                else:
                    # no-op when already built (init did it) or telemetry
                    # is off — this covers states that arrived via
                    # restore(): a resumed incarnation must not lose the
                    # derived metrics exactly on the runs telemetry is
                    # meant to post-mortem
                    self._maybe_build_accounting(batch)
                # 1-based optimizer step this iteration will run, global
                # across incarnations (resume keeps epoch/skip aligned
                # with state.step) — the coordinate PTD_FAULTS specs and
                # the preemption record are expressed in
                gstep = epoch * self._steps_per_epoch + i + 1
                if self._faults is not None:
                    self._faults.on_step(gstep)
                    # layer-targeted NaN injection (ISSUE 6): poison one
                    # layer's params so the non-finite values flow through
                    # the REAL model — the in-graph provenance
                    # (diag/first_bad_layer) must name exactly this layer
                    layer = self._faults.poison_nan_layer(gstep)
                    if layer is not None:
                        self._poison_layer_params(layer)
                self._maybe_profile(epoch, i)
                if self._profiling:
                    # step annotations ride the capture so utils/trace.py
                    # can auto-detect the step count (no more --steps=1
                    # mislabeling a 6-step window); the name is the shared
                    # contract detect_step_count matches on
                    from pytorchdistributed_tpu.utils.trace import (
                        STEP_ANNOTATION,
                    )

                    with jax.profiler.StepTraceAnnotation(STEP_ANNOTATION,
                                                          step_num=i):
                        metrics = self.train_step(batch)
                else:
                    metrics = self.train_step(batch)
                if (self._faults is not None
                        and self._faults.poison_nan(gstep)):
                    # injected numeric blowup: the tripwire must record
                    # it and the watchdog must raise at the next log sync
                    metrics = {**metrics, "loss": float("nan")}
                n = self._batch_samples(batch)
                self._meter.update(n)
                self._last_batch_samples = n
                if (i + 1) % self.log_every == 0:
                    # the blocking device sync: float() forces the chain
                    with self._span("metric_sync"):
                        vals = {k: float(v) for k, v in metrics.items()}
                    # diag/* scalars split out of the primary stream:
                    # they feed the tripwires and the diagnostics JSONL,
                    # not the console logger / telemetry metric rows
                    dvals = {}
                    if self._diag is not None:
                        dvals = {k: vals.pop(k) for k in list(vals)
                                 if k.startswith("diag/")}
                    if self._heartbeat is not None:  # we just synced
                        self._heartbeat.beat()
                    # tripwires BEFORE the watchdog: the watchdog RAISES
                    # on the same non-finite values — the durable event
                    # record must exist by then. The detector sees the
                    # merged view (per-key EMAs watch diag/* scalars and
                    # the non-finite event picks up the NaN-provenance
                    # layer index); the watchdog sees only the primary
                    # metrics — a non-finite DIAGNOSTIC (e.g. an inf
                    # absmax one layer deep) is an early warning to
                    # record, never a reason to abort before the loss
                    # itself goes bad.
                    self._check_tripwires(epoch, i + 1, {**vals, **dvals})
                    self._write_diagnostics(epoch, i + 1, gstep, dvals)
                    if self._watchdog is not None:
                        self._watchdog.check(vals, self.state)
                    rate = self._meter.rate
                    if rate == rate:  # skip the warmup NaN
                        vals["samples_per_s"] = rate
                        self._derived_metrics(vals, rate)
                    if self._telemetry_jsonl is not None:
                        self._telemetry_jsonl.write(
                            {"time": round(time.time(), 3), "epoch": epoch,
                             "step": i + 1, "rank": self._telemetry_rank,
                             **vals})
                    if dist.is_main_process():
                        self.logger.log_step(epoch, i + 1, vals)
                if (self.checkpoint is not None
                        and self._checkpoint_every > 0
                        and (i + 1) % self._checkpoint_every == 0):
                    with self._span("checkpoint"):
                        self._save_checkpoint()
                if self._preempt_requested:
                    # the current step is finished — honor the SIGTERM
                    # now: durable checkpoint, then the distinct exit
                    self._graceful_preempt(epoch, gstep)
        finally:
            # teardown runs on the exception path too: an open profiler
            # capture is closed, the JSONL sinks are flushed+closed (a
            # watchdog abort must never leave a truncated metrics file),
            # and the span trace is dumped for the post-mortem report
            self._maybe_profile(epoch, -1)
            self.logger.close()
            self._teardown_telemetry()
        out = {k: float(v) for k, v in metrics.items()}
        if self._heartbeat is not None:  # epoch-end device sync
            self._heartbeat.beat()
        return out

    def _spanned_iter(self, raw):
        """Wrap the host-side loader iterator so each batch fetch is a
        "data_load" span (only built when tracing is on)."""
        while True:
            with self._span("data_load"):
                try:
                    batch = next(raw)
                except StopIteration:
                    return
            yield batch

    def _check_tripwires(self, epoch: int, step: int, vals: dict) -> None:
        """Anomaly tripwires at log cadence: pure host arithmetic on the
        already-synced floats (no extra device blocking); each finding
        becomes a durable TelemetryEvent JSONL row before anything can
        raise."""
        if self._anomaly is None:
            return
        for kind, payload in self._anomaly.check(vals, step=step):
            ev = self._events.emit(kind, step=step, epoch=epoch, **payload)
            self.logger.info(f"telemetry tripwire: {ev.describe()}")

    def _derived_metrics(self, vals: dict, rate: float) -> None:
        """StepAccounting-derived metrics at log cadence: step time from
        the throughput window, then MFU / tokens-per-s / comm-bytes —
        plus the device-memory high-water where the backend reports one."""
        if self.accounting is None or not self._last_batch_samples:
            return
        sec = self._last_batch_samples / rate
        vals["step_time_s"] = round(sec, 6)
        tps = self.accounting.tokens_per_s(sec)
        if tps is not None:
            vals["tokens_per_s"] = tps
        mfu = self.accounting.mfu(sec)
        if mfu is not None:
            vals["mfu"] = mfu
        vals["comm_bytes_per_step"] = self.accounting.comm_bytes_per_step
        stall = self.accounting.comm_stall_frac(sec)
        if stall is not None:
            vals["comm_stall_frac"] = stall
        hw = device_memory_highwater()
        if hw is not None:
            vals["device_peak_mem_bytes"] = hw

    def _write_diagnostics(self, epoch: int, step: int, gstep: int,
                           dvals: dict) -> None:
        """Stream the diagnostics JSONL (telemetry_dir must be set):
        scalar rows at log cadence; the per-layer tables join a row
        whenever the table cadence has elapsed — the tables were computed
        in-graph with the step, so attaching them here costs one host
        conversion of already-materialized device arrays, never an extra
        dispatch."""
        if self._diag_writer is None or not (dvals
                                             or self._pending_diag_tables):
            return
        row = {"time": round(time.time(), 3), "epoch": epoch, "step": step,
               "rank": self._telemetry_rank,
               **{k: round(v, 8) for k, v in dvals.items()}}
        if (self._diag.table_every and self._pending_diag_tables
                and gstep >= self._diag_table_next):
            self._diag_table_next = gstep + self._diag.table_every
            row["layers"] = {
                k.split("/", 1)[1]:
                    [round(float(x), 6) for x in np.asarray(v).ravel()]
                for k, v in self._pending_diag_tables.items()}
        self._diag_writer.write(row)

    def _poison_layer_params(self, layer: int) -> None:
        """Fault hook (PTD_FAULTS ``nan@step=S,layer=L``): overwrite one
        param leaf's slice for ``layer`` with NaN so the blowup originates
        at that block and propagates forward like a real numeric failure.
        Scanned stacks are matched by the leading layer axis; unrolled
        stacks by their ``block_{layer}`` name. The replacement is built
        under the leaf's own sharding so the donated-state step's
        in_shardings contract is untouched."""
        cfg = self._transformer_cfg()
        nl = getattr(cfg, "num_layers", 0)
        if not 0 <= layer < max(nl, 1):
            raise ValueError(
                f"nan fault layer={layer} out of range for a model with "
                f"{nl} layers")
        # The layout question is answered by the CONFIG, never by shape
        # sniffing: an unrolled block's own leaves can carry a leading dim
        # equal to num_layers by coincidence (the fused-qkv [3, width]
        # bias at num_layers=3), which would poison the wrong layer and
        # silently break the provenance contract.
        scanned = bool(getattr(cfg, "scan_layers", False))
        done = [False]

        def pick(path, p, sh):
            if done[0] or not hasattr(p, "ndim"):
                return p
            if not jnp.issubdtype(p.dtype, jnp.floating):
                return p
            key = jax.tree_util.keystr(path)
            if scanned:
                if not ("block" in key and p.ndim >= 1
                        and p.shape[0] == nl):
                    return p
                fn = lambda x: x.at[layer].set(jnp.nan)  # noqa: E731
            else:
                if f"block_{layer}'" not in key:
                    return p
                fn = lambda x: jnp.full_like(x, jnp.nan)  # noqa: E731
            done[0] = True
            return jax.jit(fn, out_shardings=sh)(p)

        params = jax.tree_util.tree_map_with_path(
            pick, self.state.params, self.state_shardings.params)
        if not done[0]:
            raise ValueError(
                "nan fault layer targeting found no block param leaf to "
                "poison (non-transformer model?) — drop layer= to use the "
                "host-side loss poisoning instead")
        self.state = self.state.replace(params=params)

    # -- evaluation --------------------------------------------------------

    def eval_step(self, batch) -> dict:
        """Forward + loss with NO optimizer update (and no rng — dropout
        off). Jitted and cached on first use; params stay whatever
        train_step left them."""
        return {k: v for k, v in self._eval_raw(batch).items()
                if not k.startswith("_")}

    def _eval_raw(self, batch) -> dict:
        """eval_step including the underscore plumbing keys — evaluate()
        reads "_mask_count" off this to weight masked-token batches by
        token count."""
        if self.state is None:
            self.init(batch)
        if self._eval_fn is None:
            policy = self.precision

            def estep(params, batch):
                cparams = policy.cast_params_for_compute(params)
                cbatch = policy.cast_batch(batch)
                with nn.logical_axis_rules(self._rules):
                    _, metrics = self._loss_fn(self.model, cparams, cbatch,
                                               None)
                return {k: v.astype(jnp.float32)
                        for k, v in metrics.items()}

            # Explicit in_shardings, same contract as the train step: a
            # mismatched-layout batch errors instead of silently re-laying
            # out (params side reuses the state shardings).
            self._eval_fn = jax.jit(
                estep, in_shardings=(self.state_shardings.params, None),
                compiler_options=self._compiler_options)
        if any(not isinstance(v, jax.Array) for v in batch.values()):
            batch = shard_batch(batch, self.batch_sharding)
        with jax.set_mesh(self.mesh):
            metrics = self._eval_fn(self.state.params, batch)
        self._bound_dispatch_queue(metrics)
        return metrics

    def evaluate(self, loader) -> dict[str, float]:
        """Mean metrics over a validation loader (sample-weighted across
        ragged final batches — build val loaders with drop_last=False so
        every sample is scored). The epoch is pinned to 0 so successive
        evaluate() calls score the SAME subset in the same order — val
        curves stay comparable across epochs; prefer shuffle=False val
        loaders. Batch means are combined by the batch's true denominator —
        masked-token losses report theirs ("_mask_count"), everything else
        weights by sample count — so the result is the global mean over
        real masked tokens / samples, independent of batch grouping.
        Multi-replica (closes ADVICE r2): with drop_last=False the
        sampler pads replicas to equal count by repeating head indices;
        those padded duplicates are zero-weighted here — every batch
        carries a ``sample_weight`` built from `ShardedSampler.valid_mask`,
        the losses fold it into their means, and the totals weight by real
        samples — so the multi-replica eval mean equals the single-replica
        one exactly. (All-or-no batches carry the key, decided from the
        sampler's global geometry, so every replica compiles the same
        program.) Custom loss_fns: the exactness holds only if the loss
        folds ``batch["sample_weight"]`` into its means the way the
        built-in losses do (losses._sample_weight); one that ignores the
        key still counts padded duplicates — use a single-replica val
        loader there. That contract is now CHECKED, not just documented:
        on the first batch overlapping the global pad tail, the same
        program is re-dispatched with all-ones weights — a weight-folding
        loss must answer differently when some weight is zero, so
        identical metrics mean the loss ignored the key and a UserWarning
        fires. The probe batch is chosen from the sampler's GLOBAL
        geometry, so every replica of a multi-process eval dispatches the
        same extra program at the same step (no SPMD divergence); whether
        to warn is judged rank-locally (only ranks whose shard holds the
        zeros can tell). Alignment is also a contract: the padded path
        maps ``valid_mask()`` onto batches positionally, so the loader
        must yield contiguous in-order slices of
        ``sampler.local_indices()`` — a loader yielding a different total
        trips the sample count assertions instead of silently
        mis-weighting. The reference has no eval loop at all; this is the
        missing half of its Trainer."""
        totals: dict = {}
        count = 0.0
        loader.set_epoch(0)
        sampler = getattr(loader, "sampler", None)
        padded = (sampler is not None and getattr(sampler, "total_size", 0)
                  > getattr(sampler, "dataset_size", 0))
        # Host-side per-batch flags, appended by batches() as it runs
        # ahead under the prefetcher (so index i is always populated by
        # the time the consumer reads it): probe_flags marks the batches
        # overlapping the global pad tail — identical on EVERY replica
        # (derived from global geometry + the shared batching), which is
        # what lets all processes dispatch the probe in lockstep;
        # zero_flags marks where THIS rank's shard actually has zeros.
        probe_flags: list[bool] = []
        zero_flags: list[bool] = []

        def batches():
            if not padded:
                yield from loader
                return
            valid = sampler.valid_mask()
            # first locally-padded position on the ranks that carry pad
            # duplicates (the pad is a suffix of the highest ranks'
            # shards) — a global constant every rank computes identically
            first_pad = sampler.num_samples - (
                sampler.total_size - sampler.dataset_size)
            offset = 0
            for batch in loader:
                n_local = self._batch_samples(batch)
                # running offset, not b * loader.batch_size: a loader
                # whose batch_size attribute misstates its actual batch
                # width must not silently mis-slice (ADVICE r4 #2)
                w = valid[offset: offset + n_local].astype(np.float32)
                if w.size != n_local:
                    raise ValueError(
                        f"evaluate(): loader yielded more than the "
                        f"sampler's {sampler.num_samples} samples — the "
                        f"padded-weight path requires contiguous in-order "
                        f"slices of local_indices()")
                probe_flags.append(offset + n_local > first_pad)
                zero_flags.append(bool((w == 0).any()))
                offset += n_local
                yield {**batch, "sample_weight": w}
            if offset != sampler.num_samples:
                raise ValueError(
                    f"evaluate(): loader yielded {offset} samples but the "
                    f"sampler holds {sampler.num_samples} — sample weights "
                    f"would be misaligned with samples")

        weight_fold_checked = False
        for i, batch in enumerate(
                prefetch_to_device(batches(), self.batch_sharding,
                                   size=self.prefetch)):
            metrics = self._eval_raw(batch)
            if padded and not weight_fold_checked and probe_flags[i]:
                # The sample_weight contract guard (VERDICT r4 weak #5):
                # somewhere in this global batch sit zero-weighted pad
                # duplicates, so a loss that folds weights MUST answer
                # differently under all-ones weights. Same pytree
                # structure — re-dispatch, no recompile; once per
                # evaluate(), on every replica in lockstep.
                weight_fold_checked = True
                probe = self._eval_raw(
                    {**batch, "sample_weight":
                     jnp.ones_like(batch["sample_weight"])})
                if zero_flags[i] and metrics and all(
                        np.array_equal(np.asarray(metrics[k]),
                                       np.asarray(probe[k]))
                        for k in metrics):
                    import warnings

                    warnings.warn(
                        "evaluate(): the loss_fn ignored "
                        "batch['sample_weight'] — padded duplicate "
                        "samples are being counted and the multi-replica "
                        "eval mean is skewed. Fold the weight like "
                        "training/losses.py does, or evaluate on a "
                        "single-replica loader.", stacklevel=2)
            # batch weight, most-exact first: masked-token losses report
            # their token count ("_mask_count" — weighting batch means by
            # it reproduces the global masked-token mean exactly across any
            # batch/replica grouping); else the real-sample count (the
            # pad-excluding weight sum, device-lazy) / the global batch size
            wtok = metrics.pop("_mask_count", None)
            if wtok is not None:
                n = wtok
            elif padded:
                n = batch["sample_weight"].astype(jnp.float32).sum()
            else:
                n = self._batch_samples(batch)
            for k, v in metrics.items():
                # device-side accumulation: a per-batch float() here would
                # block the host each step and defeat the prefetch overlap
                totals[k] = totals.get(k, 0.0) + v * n
            count += n
        count = float(count)
        if count == 0:
            return {}
        out = {k: float(v) / count for k, v in totals.items()}
        if dist.is_main_process():
            self.logger.info(
                "eval | " + " ".join(f"{k}={v:.4g}" for k, v in out.items()))
        return out

    @property
    def throughput(self) -> float:
        """samples/s over the recent window (compile step excluded)."""
        return self._meter.rate

    @staticmethod
    def _batch_samples(batch) -> int:
        return next(int(v.shape[0]) for v in batch.values()
                    if hasattr(v, "shape") and v.ndim > 0)

    def _maybe_profile(self, epoch: int, step: int) -> None:
        """With profile_dir set, capture a device trace of steps 2-7 of the
        first epoch (past compile, short enough to open in Perfetto)."""
        if self.profile_dir is None or epoch != 0:
            return
        if step == 2 and not self._profiling:
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        elif self._profiling and (step >= 8 or step < 0):
            jax.profiler.stop_trace()
            self._profiling = False
            if dist.is_main_process():
                self.logger.info(f"profile trace written to "
                                 f"{self.profile_dir}")

    def _save_checkpoint(self, *, force: bool = False) -> None:
        """Save unless this step is already on disk (an epoch-end save can
        land on the same step as the last interval save). A JSON sidecar
        records steps_per_epoch so resume can detect a changed loader
        geometry (different batch size / replica count) instead of silently
        skipping the wrong number of batches. The sidecar is written
        atomically (temp + os.replace): a rank killed mid-write must leave
        either the whole meta file or none — a truncated one would brick
        the very resume it exists to guard."""
        step = int(self.state.step)
        if step in self.checkpoint.all_steps():
            return
        if self.checkpoint.save(step, self.state, force=force) \
                and self._steps_per_epoch and dist.is_main_process():
            meta = {"steps_per_epoch": self._steps_per_epoch}
            path = self.checkpoint.directory / f"trainer_meta_{step}.json"
            tmp = path.with_name(path.name + ".tmp")
            tmp.write_text(json.dumps(meta))
            os.replace(tmp, path)

    # -- preemption --------------------------------------------------------

    def _on_sigterm(self, signum, frame) -> None:
        """Signal handler: flag only — all real work (device sync,
        checkpoint I/O) happens at the next safe point in the step loop,
        never inside the handler."""
        self._preempt_requested = True

    def _install_preempt_handler(self):
        """SIGTERM → graceful preemption while fit() runs (TPU preemption
        notice / run.py --preempt-grace forwarding). Returns a restore
        callback; no-op off the main thread (signal API limitation) and
        under callers that already own SIGTERM."""
        if threading.current_thread() is not threading.main_thread():
            return lambda: None
        try:
            prev = signal.signal(signal.SIGTERM, self._on_sigterm)
        except ValueError:  # pragma: no cover - non-main interpreter state
            return lambda: None
        return lambda: signal.signal(signal.SIGTERM, prev)

    def _graceful_preempt(self, epoch: int, step: int) -> None:
        """The SIGTERM contract: the current step has completed — record
        the preemption, force a checkpoint, block until it is durable
        (keepalive beats so the agent's hung-rank detector doesn't kill
        the drain), then exit with the distinct PREEMPTED code the
        launcher never charges to the same-rank failure tracker."""
        self.logger.info(
            f"preempted (SIGTERM) at step {step}; draining checkpoint")
        if self._events is not None:
            self._events.emit(EVENT_PREEMPTED, step=step, epoch=epoch)
            self._events.flush()
        if self.checkpoint is not None:
            hb = (self._heartbeat.keepalive()
                  if self._heartbeat is not None
                  else contextlib.nullcontext())
            with hb, self._span("preempt_checkpoint"):
                self._save_checkpoint(force=True)
                self.checkpoint.wait()
        raise SystemExit(EXIT_PREEMPTED)

    def fit(self, loader, max_epochs: int, *,
            resume: bool = False, val_loader=None) -> dict[str, float]:
        """The reference's ``train`` (ddp_gpus.py:53-55), plus
        checkpoint/resume (SURVEY.md §5): with a checkpoint_dir configured,
        every epoch end saves the sharded state async, and ``resume=True``
        continues from the latest VERIFIED step — a corrupt newest
        checkpoint is quarantined and the previous one loads instead of
        the run dying. While fit runs, SIGTERM means preemption: the
        current step finishes, a checkpoint is forced durable, and the
        process exits EXIT_PREEMPTED. ``val_loader`` runs evaluate() at
        every epoch end; its metrics land in the return dict as val_*."""
        restore_handler = self._install_preempt_handler()
        try:
            return self._fit(loader, max_epochs, resume=resume,
                             val_loader=val_loader)
        finally:
            restore_handler()

    def _fit(self, loader, max_epochs: int, *,
             resume: bool, val_loader) -> dict[str, float]:
        start_epoch, skip = 0, 0
        if resume:
            if self.checkpoint is None:
                raise ValueError(
                    "fit(resume=True) needs a checkpoint_dir — none is "
                    "configured, so there is nothing to resume from")
            if self.checkpoint.latest_step() is None:
                # Empty (or typo'd) directory: surface it loudly instead of
                # silently training from scratch.
                self.logger.info(
                    f"WARNING: resume=True but no checkpoint under "
                    f"{self.checkpoint.directory}; training from scratch")
            else:
                start_epoch, skip = self._resume(loader)
        metrics = {}
        for epoch in range(start_epoch, max_epochs):
            t0 = time.perf_counter()
            metrics = self.run_epoch(
                loader, epoch, skip_steps=skip if epoch == start_epoch else 0)
            if val_loader is not None:
                metrics.update({f"val_{k}": v for k, v in
                                self.evaluate(val_loader).items()})
            if self.checkpoint is not None:
                with self._span("checkpoint"):
                    self._save_checkpoint(force=True)
            if dist.is_main_process():
                self.logger.info(
                    f"epoch {epoch} done in {time.perf_counter() - t0:.2f}s "
                    f"| {metrics}"
                )
        if self.checkpoint is not None:
            self.checkpoint.wait()
        self._teardown_telemetry()  # pick up the epoch-end checkpoint spans
        return metrics

    def restore(self, sample_batch=None, *, step: int | None = None):
        """Load a checkpoint into this Trainer WITHOUT a fit loop — the
        `load_state_dict` analog for evaluation or generation:

            tr = Trainer(model, opt, loss, checkpoint_dir=d)
            tr.restore(sample_batch)
            tr.evaluate(val_loader)          # or
            generate(decode_model, tr.state.params, prompt, ...)

        ``sample_batch`` shapes the abstract state (params are never
        materialized at init values — the abstract half of init() feeds the
        checkpoint reader directly); ``step`` picks a checkpoint (default:
        latest). Restoring re-shards onto THIS Trainer's mesh/strategy even
        if the saving run used a different one. Returns the TrainState."""
        from pytorchdistributed_tpu.training.checkpoint import (
            abstract_state_like,
        )

        if self.checkpoint is None:
            raise ValueError("restore() needs a checkpoint_dir")
        if step is None and self.checkpoint.latest_step() is None:
            raise ValueError(
                f"no checkpoint under {self.checkpoint.directory}")
        if self.state is None:
            if sample_batch is None:
                raise ValueError(
                    "restore() on an uninitialized Trainer needs a "
                    "sample_batch to shape the abstract state")
            abstract = self._prepare_abstract(sample_batch,
                                              jax.random.key(0))
        else:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.state)
            self.state = None  # free the live buffers BEFORE orbax
            # allocates the restored state — otherwise a model sized near
            # HBM capacity holds 2x params+opt_state during the load
        abstract_sharded = abstract_state_like(abstract, self.state_shardings)
        if step is not None:
            # pinned step: strict — verification failure raises rather
            # than silently answering with a different checkpoint
            self.state = self.checkpoint.restore(abstract_sharded, step=step)
        else:
            # default: the verified-fallback chain — corrupt steps are
            # quarantined and the walk continues to the last good one
            newest = self.checkpoint.latest_step()
            try:
                self.state, restored = self.checkpoint.restore_verified(
                    abstract_sharded)
            except FileNotFoundError as e:
                raise ValueError(str(e)) from None
            if restored != newest and dist.is_main_process():
                self.logger.info(
                    f"restore fell back to step {restored} (newest step "
                    f"{newest} failed verification; quarantined)")
        # The train step builds lazily on the first train_step() — eager
        # building here would let train-only guards (accum x 1f1b, dropout
        # in pipelines) break inference-only restores.
        if dist.is_main_process():
            self.logger.info(f"restored step {int(self.state.step)} from "
                             f"{self.checkpoint.directory}")
        return self.state

    def _resume(self, loader) -> tuple[int, int]:
        """Restore the latest VERIFIED checkpoint (re-sharding onto the
        current mesh if it differs from the saving run's; corrupt steps
        fall back — see restore()). Returns (epoch to resume at, batches
        of that epoch to skip) — a mid-epoch checkpoint fast-forwards
        past the already-trained prefix so no batch is trained twice.
        The geometry guard runs against the step that actually restored:
        a missing or torn trainer_meta sidecar downgrades to a warning
        (the state itself is integrity-checked; losing the sidecar must
        not brick resume), a PRESENT sidecar that contradicts the loader
        still raises."""
        if self.state is None:  # restore() only reads the batch in this case
            loader.set_epoch(0)
            self.restore(next(iter(loader)))
        else:
            self.restore()
        step = int(self.state.step)
        meta_path = self.checkpoint.directory / f"trainer_meta_{step}.json"
        saved = None
        try:
            saved = json.loads(meta_path.read_text()).get("steps_per_epoch")
        except FileNotFoundError:
            self.logger.info(
                f"WARNING: no trainer_meta_{step}.json sidecar; skipping "
                f"the loader-geometry check for this resume")
        except (OSError, ValueError):
            self.logger.info(
                f"WARNING: unreadable trainer_meta_{step}.json (torn "
                f"write?); skipping the loader-geometry check for this "
                f"resume")
        if saved and saved != len(loader):
            raise ValueError(
                f"checkpoint at step {step} was written with "
                f"steps_per_epoch={saved} but the current loader has "
                f"{len(loader)} — resuming would skip the wrong batches "
                f"or retrain duplicates; use the same batch size and "
                f"replica count as the saving run")
        steps_per_epoch = max(len(loader), 1)
        start_epoch = step // steps_per_epoch
        skip = step % steps_per_epoch
        if dist.is_main_process():
            self.logger.info(f"resumed from step {step} "
                             f"(epoch {start_epoch}, skipping {skip})")
        return start_epoch, skip


def _drop_sown(variables):
    """Strip the sown per-batch OUTPUT collections a `model.init` may have
    created ("losses" — Switch-MoE aux values; "diagnostics" — the
    in-graph health stats, which the block sow sites already skip at init
    but are dropped here too for defense in depth): they are not state —
    keeping them in TrainState would allocate optimizer slots for them
    and break the 1F1B grad merge (pipeline_parts grads cover "params"
    only)."""
    return {k: v for k, v in variables.items()
            if k not in ("losses", "diagnostics")}


def _opt_state_shardings(abstract_opt_state, abstract_params, param_shardings,
                         mesh):
    """Optimizer slots that mirror the parameter pytree (momentum, adam m/v)
    inherit the parameter shardings leaf-for-leaf — ZeRO's optimizer-state
    partitioning. Matching is *structural* (same treedef and leaf shapes),
    never by shape lookup: same-shaped params can carry different shardings
    under TP. Anything else (step counters, schedules) is replicated."""
    target = jax.tree.structure(abstract_params)
    param_shapes = [p.shape for p in jax.tree.leaves(abstract_params)]

    def mirrors_params(node):
        try:
            if jax.tree.structure(node) != target:
                return False
            return [l.shape for l in jax.tree.leaves(node)] == param_shapes
        except Exception:
            return False

    def pick(node):
        if mirrors_params(node):
            return param_shardings
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), node)

    return jax.tree.map(pick, abstract_opt_state, is_leaf=mirrors_params)
