"""Rank-0 structured logging (SURVEY.md §5 "Metrics / logging").

The reference prints from every rank, interleaving output
(02_ddp.ipynb:252-266). Here: a stdlib logger that only emits on the main
process, plus a tiny metric formatter, plus an optional machine-readable
JSONL sink (``jsonl_path`` / Trainer ``metrics_file``) so per-step metrics
are first-class data, not just console text. The sink is a `JsonlWriter`
— lazy-open, line-buffered, idempotent ``close()`` with reopen-on-next-
write — shared with the telemetry subsystem's per-rank metric files.
Heavier sinks (TensorBoard via `jax.profiler`) attach in
utils/profiling.py.
"""

from __future__ import annotations

import logging
import sys
import time

import jax

# The one JSONL-durability implementation (lazy reopen, line-buffered,
# idempotent close) lives with the telemetry subsystem; re-exported here
# so training-side callers keep their import path.
from pytorchdistributed_tpu.telemetry.events import JsonlWriter  # noqa: F401

_FMT = "[%(asctime)s rank{rank}] %(message)s"


class MetricLogger:
    """Console (rank-tagged) + optional JSONL metrics. Context-manager
    and ``close()`` support close the JSONL sink (the stdlib handler
    stays — it belongs to the process); a closed logger transparently
    reopens its sink on the next ``log_step``, so per-epoch teardown
    close() composes with multi-epoch ``fit``."""

    def __init__(self, name: str = "tpu-dist", jsonl_path: str | None = None):
        self._log = logging.getLogger(name)
        if not self._log.handlers:
            h = logging.StreamHandler(sys.stdout)
            h.setFormatter(
                logging.Formatter(
                    _FMT.format(rank=jax.process_index()), "%H:%M:%S"
                )
            )
            self._log.addHandler(h)
            self._log.setLevel(logging.INFO)
            self._log.propagate = False
        self._jsonl = JsonlWriter(jsonl_path) if jsonl_path else None

    def info(self, msg: str) -> None:
        self._log.info(msg)

    def log_step(self, epoch: int, step: int, metrics: dict[str, float]) -> None:
        parts = " ".join(f"{k}={v:.4g}" for k, v in metrics.items())
        self._log.info(f"epoch {epoch} step {step} | {parts}")
        if self._jsonl is not None:
            self._jsonl.write(
                {"time": round(time.time(), 3), "epoch": epoch, "step": step,
                 **{k: float(v) for k, v in metrics.items()}})

    def flush(self) -> None:
        if self._jsonl is not None:
            self._jsonl.flush()

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
