"""Rank-0 structured logging (SURVEY.md §5 "Metrics / logging").

The reference prints from every rank, interleaving output
(02_ddp.ipynb:252-266). Here: a stdlib logger that only emits on the main
process, plus a tiny metric formatter, plus an optional machine-readable
JSONL sink (``jsonl_path`` / Trainer ``metrics_file``) so per-step metrics
are first-class data, not just console text. Heavier sinks (TensorBoard
via `jax.profiler`) attach in utils/profiling.py.
"""

from __future__ import annotations

import json
import logging
import sys
import time

import jax

_FMT = "[%(asctime)s rank{rank}] %(message)s"


class MetricLogger:
    def __init__(self, name: str = "tpu-dist", jsonl_path: str | None = None):
        self._log = logging.getLogger(name)
        if not self._log.handlers:
            h = logging.StreamHandler(sys.stdout)
            h.setFormatter(
                logging.Formatter(
                    _FMT.format(rank=jax.process_index()), "%H:%M:%S"
                )
            )
            self._log.addHandler(h)
            self._log.setLevel(logging.INFO)
            self._log.propagate = False
        # line-buffered append: each step is one durable JSON line even if
        # the job dies mid-epoch
        self._jsonl = (open(jsonl_path, "a", buffering=1)
                       if jsonl_path else None)

    def info(self, msg: str) -> None:
        self._log.info(msg)

    def log_step(self, epoch: int, step: int, metrics: dict[str, float]) -> None:
        parts = " ".join(f"{k}={v:.4g}" for k, v in metrics.items())
        self._log.info(f"epoch {epoch} step {step} | {parts}")
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(
                {"time": round(time.time(), 3), "epoch": epoch, "step": step,
                 **{k: float(v) for k, v in metrics.items()}}) + "\n")
