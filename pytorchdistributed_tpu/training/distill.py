"""Draft distillation (ISSUE 16): train a cheap speculative draft on
KL-to-target-logits over a logged-traffic corpus.

PR 8's speculative decode is lossless with ANY draft — quality only moves
the acceptance rate, and with it the decode-rate multiplier. This module
closes the learning half of that loop with three pieces that reuse the
existing machinery unchanged:

  * ``distill_loss`` — KL(teacher || student) per position, per proposal
    offset: the base head matches the teacher's next-token distribution
    at the same position, proposal head j (the Medusa recipe, Cai et al.
    2024) matches the teacher's distribution j positions AHEAD — the
    teacher-forced shifted target that one teacher forward yields for
    every head at once. A standard Trainer ``loss_fn`` signature, so the
    whole Trainer loop (accum, checkpointing, telemetry, diagnostics,
    fault tolerance) rides along untouched.
  * ``distill_corpus`` — batches from serving/traffic.py's deterministic
    trace generator: the student trains on the prompt/length mix the
    fleet actually serves, continued BY the target (the behavior being
    distilled), with the teacher's log-probs precomputed once per batch.
  * ``DistillTrainer`` — the thin wrapper: builds the student via
    inference.make_draft (truncated-draft warm start for the block
    weights, zero-init proposal heads), swaps the warm start into the
    Trainer's freshly-initialized state, and hands back
    ``(draft_config, draft_params)`` ready for ServingEngine /
    ``router.set_draft_params`` hot-swap.

The TARGET is frozen by construction, not by optimizer masking: its
params are only ever READ (warm start + corpus teacher); the Trainer
only ever sees the student.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorchdistributed_tpu.training.losses import (
    _apply_collecting,
    _diag_extras,
    _stochastic_kwargs,
)
from pytorchdistributed_tpu.training.trainer import Trainer


def distill_loss(model, params, batch, rng=None, *, diagnostics=False):
    """KL(teacher || student) over every proposal offset in one forward.

    batch = {tokens [b, s] int32,
             target_logprobs [b, s, V] fp32 — the teacher's log-softmax
               at every position (position i predicts token i+1),
             loss_mask [b, s] optional — 1 where the teacher row is a
               real prediction position}.

    A student with ``cfg.spec_heads == H > 0`` runs ``spec_logits`` —
    [b, s, H+1, V], index 0 the base head — and offset o trains
    position i against the teacher at position i+o (the token i+o+1
    both are predicting). H == 0 degrades to plain next-token
    distillation. The scalar loss is the masked mean over ALL
    (position, offset) pairs; metrics carry the per-offset means so a
    distill run shows which head is lagging. Full-vocab teacher rows
    are CPU-sized-corpus honest; a production-vocab corpus would ship
    top-k + tail mass instead (same loss shape).
    """
    H = int(getattr(model.cfg, "spec_heads", 0))
    if H:
        method = type(model).spec_logits
        out, mods = _apply_collecting(
            model, params, batch["tokens"], diagnostics=diagnostics,
            method=method, **_stochastic_kwargs(method, rng))
    else:
        out, mods = _apply_collecting(
            model, params, batch["tokens"], diagnostics=diagnostics,
            **_stochastic_kwargs(type(model).__call__, rng))
        out = out[..., None, :]
    tlp = batch["target_logprobs"].astype(jnp.float32)    # [b, s, V]
    tp = jnp.exp(tlp)
    s = tlp.shape[1]
    mask = batch.get("loss_mask")
    base_m = (jnp.ones(tlp.shape[:2], jnp.float32) if mask is None
              else mask.astype(jnp.float32))
    total = jnp.float32(0.0)
    count = jnp.float32(0.0)
    metrics = {}
    for o in range(H + 1):
        # student at position i (head o) vs teacher at position i + o
        slp = jax.nn.log_softmax(
            out[:, :s - o, o, :].astype(jnp.float32), axis=-1)
        kl = (tp[:, o:] * (tlp[:, o:] - slp)).sum(-1)     # [b, s - o]
        m = base_m[:, o:]
        # where, not bare multiply: non-finite KL at a masked position
        # (padding garbage) must drop, and inf * 0.0 is NaN
        kl = jnp.where(m > 0, kl, 0.0)
        total = total + (kl * m).sum()
        count = count + m.sum()
        name = "kl_base" if o == 0 else f"kl_head{o}"
        metrics[name] = (kl * m).sum() / jnp.maximum(m.sum(), 1.0)
    loss = total / jnp.maximum(count, 1.0)
    # _mask_count: the grad-accumulation weight, exactly the
    # _token_loss_reduce contract (losses.py) — masked micro-batches
    # must reproduce the full-batch masked mean
    return loss, {"loss": loss, "_mask_count": count, **metrics,
                  **_diag_extras(mods, diagnostics)}


def distill_corpus(model, params, *, seed: int = 0, num_batches: int = 8,
                   batch_size: int = 8, seq_len: int = 64,
                   max_new_tokens: int = 16, base_qps: float = 64.0,
                   prompt_cap: int | None = None):
    """Logged-traffic distillation batches: ``num_batches`` lists of
    {tokens, target_logprobs, loss_mask}, deterministic per ``seed``.

    Prompts come from serving/traffic.py's trace generator (the same
    length/arrival mix the replay harness drives at the fleet), each
    continued by the TARGET with greedy decode — the student distills
    the behavior the fleet actually emits, not held-out text — and the
    teacher's per-position log-probs come from ONE batched target
    forward per corpus batch. Rows are right-padded to ``seq_len`` with
    the pad masked out (and the final real token, which predicts
    nothing)."""
    from pytorchdistributed_tpu.inference import generate_bucketed
    from pytorchdistributed_tpu.serving.traffic import make_trace

    cfg = model.cfg
    if seq_len > cfg.max_seq_len:
        raise ValueError(
            f"seq_len {seq_len} > model max_seq_len {cfg.max_seq_len}")
    cap = prompt_cap or max(4, seq_len - max_new_tokens)
    if cap + max_new_tokens > seq_len:
        raise ValueError(
            f"prompt_cap {cap} + max_new_tokens {max_new_tokens} "
            f"exceeds seq_len {seq_len}")
    need = num_batches * batch_size
    trace = make_trace(
        seed=seed, duration_s=need / base_qps * 1.5 + 1.0,
        base_qps=base_qps, vocab_size=cfg.vocab_size,
        prompt_cap=cap, new_cap=max_new_tokens)
    if len(trace) < need:
        raise ValueError(
            f"trace yielded {len(trace)} requests < {need} needed — "
            f"raise base_qps or lower num_batches x batch_size")
    weights = params["params"] if "params" in params else params
    dec = (model if cfg.decode
           else model.clone(cfg=dataclasses.replace(cfg, decode=True)))
    teacher = (model if not cfg.decode
               else model.clone(cfg=dataclasses.replace(cfg, decode=False)))

    @jax.jit
    def teacher_logprobs(w, toks):
        logits = teacher.apply({"params": w}, toks).astype(jnp.float32)
        return jax.nn.log_softmax(logits, axis=-1)

    batches = []
    reqs = trace[:need]
    for b in range(num_batches):
        rows = np.zeros((batch_size, seq_len), np.int32)
        mask = np.zeros((batch_size, seq_len), np.float32)
        for i, req in enumerate(reqs[b * batch_size:(b + 1) * batch_size]):
            prompt = req.prompt[None]
            out = np.asarray(generate_bucketed(
                dec, {"params": weights}, jnp.asarray(prompt),
                max_new_tokens=min(req.max_new_tokens, max_new_tokens)))
            row = out[0][:seq_len]
            rows[i, :row.size] = row
            mask[i, :row.size - 1] = 1.0  # last token predicts nothing
        tlp = np.asarray(teacher_logprobs(weights, jnp.asarray(rows)))
        batches.append({"tokens": rows, "target_logprobs": tlp,
                        "loss_mask": mask})
    return batches


class DistillTrainer:
    """Trainer wrapper that distills a speculative draft from a frozen
    target (ISSUE 16). Construction mirrors inference.make_draft:
    ``num_layers`` truncates the target's block stack (the free warm
    start), ``spec_heads`` attaches zero-init multi-token proposal
    heads; the student then trains under the UNCHANGED Trainer — every
    trainer_kwarg (checkpoint_dir, telemetry_dir, diagnostics, strategy,
    accum_steps ...) works exactly as on a full model, because the
    Trainer cannot tell the difference.

    Usage::

        dt = DistillTrainer(target, params, num_layers=1, spec_heads=3,
                            checkpoint_dir=ckpt)
        corpus = distill_corpus(target, params, seed=0)
        dt.init(corpus[0])
        for epoch in range(epochs):
            for batch in corpus:
                dt.train_step(batch)
        draft_config, draft_params = dt.draft()   # -> ServingEngine /
                                                  #    set_draft_params
    """

    def __init__(self, model, params, *, num_layers: int | None = None,
                 spec_heads: int = 0, optimizer=None, seed: int = 0,
                 **trainer_kwargs):
        from pytorchdistributed_tpu.inference import make_draft

        draft, dparams = make_draft(model, params, num_layers=num_layers,
                                    spec_heads=spec_heads, seed=seed)
        #: the SERVE-time draft config (inherits the target's decode
        #: knobs) — what ServingEngine(draft_config=...) wants
        self.draft_config = draft.cfg
        # the student trains decode-OFF: no cache collection in its
        # train-time tree, full-sequence forwards
        self.student = draft.clone(cfg=dataclasses.replace(
            draft.cfg, decode=False))
        # callers may hand boxed (LogicallyPartitioned) init output —
        # the Trainer state is unboxed, so the warm graft must be too
        self._warm = nn.meta.unbox(dparams["params"])
        if optimizer is None:
            optimizer = optax.adamw(1e-3)
        self.trainer = Trainer(self.student, optimizer, distill_loss,
                               **trainer_kwargs)

    def init(self, sample_batch, seed: int = 0):
        """Trainer.init, then the warm start (truncated target blocks +
        zero-init heads) swapped over the fresh params — optimizer
        moments stay zero-init, which is exactly right for a warm
        start."""
        state = self.trainer.init(sample_batch, seed)
        # state.params keeps the collection wrapper ({"params": ...}, plus
        # batch_stats when present) — graft the warm tree over just the
        # "params" collection, onto the Trainer's shardings
        grafted = dict(state.params)
        # jnp.copy, not the arrays themselves: the warm tree aliases the
        # CALLER's target params (make_draft shares embed/ln_f leaves), and
        # the donated train step would free them through the alias —
        # device_put alone is an identity when the sharding already matches
        grafted["params"] = jax.tree.map(jnp.copy, self._warm)
        warm = jax.device_put(grafted, self.trainer.state_shardings.params)
        self.trainer.state = state.replace(params=warm)
        return self.trainer.state

    # -- Trainer passthroughs (the wrapper adds nothing to the loop) ----

    @property
    def state(self):
        return self.trainer.state

    @property
    def checkpoint(self):
        return self.trainer.checkpoint

    def train_step(self, batch):
        return self.trainer.train_step(batch)

    def fit(self, loader, max_epochs: int, **kw):
        return self.trainer.fit(loader, max_epochs, **kw)

    def restore(self, *a, **kw):
        return self.trainer.restore(*a, **kw)

    def evaluate(self, loader):
        return self.trainer.evaluate(loader)

    def draft(self):
        """(draft_config, draft_params) at the CURRENT step — drop
        straight into ServingEngine(spec_k=..., draft_config=...,
        draft_params=...) or engine/router ``set_draft_params`` (the
        hot-swap path; architecture matches by construction)."""
        # state.params already carries the collection wrapper
        # ({"params": ...}); device_get also severs aliasing with the
        # trainer's donated state
        return self.draft_config, jax.device_get(self.trainer.state.params)
