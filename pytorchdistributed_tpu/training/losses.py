"""Loss functions for the built-in task shapes.

The reference's only loss is `F.cross_entropy` / MSE-style regression in the
DDP hot loop (reference ddp_gpus.py:37-42). Losses here are mean-reduced over
the *global* batch: under a sharded batch inside `jit`, the mean lowers to a
local partial sum + `psum` — exactly DDP's gradient-averaging semantics
without a Reducer.

Dropout contract: the Trainer's per-step rng arrives as ``rng``; when set
(training) models that declare ``deterministic`` run with
``deterministic=False`` and a "dropout" rng stream, when None (eval_step)
they run deterministic — so ``dropout_rate > 0`` configs actually drop
units during training and are reproducible at eval.
"""

from __future__ import annotations

import inspect

import jax.numpy as jnp
import optax


def _sample_weight(batch):
    """Optional per-sample weight [batch] in fp32, else None. Injected by
    `Trainer.evaluate` to zero out the wrap-around padding samples a
    multi-replica `ShardedSampler` appends with drop_last=False (ADVICE r2:
    those duplicates used to be counted in eval means)."""
    w = batch.get("sample_weight")
    return None if w is None else w.astype(jnp.float32)


def _weighted_scalar(values, w):
    """Mean of per-sample ``values`` [batch], weighted by ``w`` (or plain
    mean when no weights ride the batch)."""
    if w is None:
        return values.mean()
    return (values.astype(jnp.float32) * w).sum() / jnp.maximum(w.sum(), 1.0)


def _token_loss_reduce(ce, batch):
    """Reduce per-token CE [batch, seq] to the scalar loss, combining the
    MLM ``loss_mask`` with the eval-time ``sample_weight``. Returns
    ``(loss, extras)`` where extras carries ``_mask_count`` (the number of
    tokens the loss was normalized over) whenever any masking applied —
    the Trainer's gradient-accumulation path weights per-micro-batch grads
    by it so accum_steps>1 reproduces the full-batch masked mean EXACTLY
    (the same global-normalization trick PipelineParts.targets_of uses on
    the 1F1B path); underscore keys never reach logs or eval totals."""
    mask = batch.get("loss_mask")
    w = _sample_weight(batch)
    if mask is None and w is None:
        return ce.mean(), {}
    m = jnp.ones(ce.shape, jnp.float32)
    if mask is not None:
        m = m * mask.astype(jnp.float32)
    if w is not None:
        m = m * w[:, None]
    count = m.sum()
    # where, not bare multiply: a non-finite CE at a masked-out position
    # (bf16 logit overflow on padding garbage) must be dropped, and
    # inf * 0.0 would be NaN
    ce = jnp.where(m > 0, ce, 0.0)
    loss = (ce * m).sum() / jnp.maximum(count, 1.0)
    # _mask_count carries the UNclamped sum: a fully-masked-out micro-batch
    # contributes zero weight to the accumulated grads, keeping the global
    # normalization exact
    return loss, {"_mask_count": count}


def _apply_collecting(model, params, *args, diagnostics=False,
                      mutable=(), **kwargs):
    """``model.apply`` that optionally opens the "diagnostics" collection
    (the in-graph health stats the transformer blocks sow — ISSUE 6) on
    top of whatever mutable collections the loss already needs. Returns
    ``(output, mods)`` where ``mods`` is {} when nothing was mutable, so
    call sites stay one-shape. The Trainer requests ``diagnostics=True``
    only when its diagnostics knob is on AND the loss advertises the
    kwarg — losses without it keep their exact pre-ISSUE-6 signature and
    traced program."""
    cols = list(mutable)
    if diagnostics:
        cols.append("diagnostics")
    if cols:
        return model.apply(params, *args, mutable=cols, **kwargs)
    return model.apply(params, *args, **kwargs), {}


def _diag_extras(mods, diagnostics):
    """The "_diag_acts" plumbing key (trainer-bound, never logged): the
    raw sown collection the train step hands to
    telemetry.diagnostics.diagnostics_metrics."""
    if not diagnostics:
        return {}
    return {"_diag_acts": dict(mods.get("diagnostics", {}))}


def _stochastic_kwargs(target, rng):
    """(kwargs for model.apply) selecting train-mode behavior when ``rng``
    is set: only for methods that take ``deterministic``. That flag now
    gates more than dropout — ResNet's ``deterministic`` switches its
    sync-BN between batch statistics (training; feeds the EMA) and the EMA
    itself (eval), so narrowing this check would silently freeze BN at
    init stats. MLP/toys have no ``deterministic`` and get no kwargs.
    ``target`` is the callable being applied (a Module's __call__ or a
    method like loss_per_position)."""
    if rng is None:
        return {}
    if "deterministic" not in inspect.signature(target).parameters:
        return {}
    return {"deterministic": False, "rngs": {"dropout": rng}}


def mse_loss(model, params, batch, rng=None):
    pred = model.apply(params, batch["x"])
    sq = (pred - batch["y"]) ** 2
    per_sample = sq.reshape(sq.shape[0], -1).mean(-1)
    loss = _weighted_scalar(per_sample, _sample_weight(batch))
    return loss, {"loss": loss}


def cross_entropy_loss(model, params, batch, rng=None, *,
                       diagnostics=False):
    """Image classification: batch = {image, label}. When training (rng
    set), models carrying normalization EMA state (ResNet's "batch_stats")
    refresh it; the updated collection rides the metrics under
    "_collections" — the Trainer pops it and folds it into TrainState
    (the flax mutable-collections train-step pattern)."""
    kwargs = _stochastic_kwargs(type(model).__call__, rng)
    mutable = (["batch_stats"]
               if rng is not None and "batch_stats" in params else [])
    logits, mods = _apply_collecting(model, params, batch["image"],
                                     diagnostics=diagnostics,
                                     mutable=mutable, **kwargs)
    w = _sample_weight(batch)
    loss = _weighted_scalar(
        optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), batch["label"]), w)
    acc = _weighted_scalar(logits.argmax(-1) == batch["label"], w)
    metrics = {"loss": loss, "accuracy": acc,
               **_diag_extras(mods, diagnostics)}
    if mutable:
        metrics["_collections"] = {k: v for k, v in mods.items()
                                   if k != "diagnostics"}
    return loss, metrics


def token_cross_entropy_loss(model, params, batch, rng=None, *,
                             diagnostics=False):
    """LM: batch = {tokens, targets}; optional {loss_mask} for MLM."""
    logits, mods = _apply_collecting(
        model, params, batch["tokens"], diagnostics=diagnostics,
        **_stochastic_kwargs(type(model).__call__, rng))
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["targets"]
    )
    loss, extras = _token_loss_reduce(ce, batch)
    return loss, {"loss": loss, **extras,
                  **_diag_extras(mods, diagnostics)}


def fused_token_cross_entropy_loss(model, params, batch, rng=None, *,
                                   diagnostics=False):
    """`token_cross_entropy_loss` through the model's fused chunked-CE head
    (GPT2/Llama `loss_per_position`): the LM head never materializes the
    fp32 ``[batch, seq, vocab]`` logits — ops/fused_ce.py measured the head
    alone at 47 → 123 TFLOP/s on v5e. Same {tokens, targets, loss_mask?}
    contract and the same math (logsumexp CE in fp32) as the unfused loss;
    use for DP/FSDP training of LM models that define `loss_per_position`.
    """
    ce, mods = _apply_collecting(
        model, params, batch["tokens"], batch["targets"],
        diagnostics=diagnostics,
        method=type(model).loss_per_position,
        **_stochastic_kwargs(type(model).loss_per_position, rng))
    loss, extras = _token_loss_reduce(ce, batch)
    return loss, {"loss": loss, **extras,
                  **_diag_extras(mods, diagnostics)}


MOE_AUX_WEIGHT = 0.01    # Switch Transformer's load-balance coefficient
MOE_ZLOSS_WEIGHT = 1e-3  # ST-MoE router z-loss coefficient


def _moe_sown_terms(losses_col):
    """Split one apply's sown "losses" collection into its two MoE terms
    by leaf NAME — ``moe_zloss`` leaves vs everything else (the
    load-balance aux) — each mean-reduced over layers. models/moe.py sows
    both under the same collection; summing them blindly would let the
    z-loss ride the aux weight."""
    import jax

    aux, z = [], []

    def walk(node):
        for key, v in node.items():
            if hasattr(v, "items"):
                walk(v)
            elif key == "moe_zloss":
                z.extend(jax.tree.leaves(v))
            else:
                aux.extend(jax.tree.leaves(v))

    walk(losses_col)

    def mean_of(leaves):
        if not leaves:
            return jnp.float32(0.0)
        return sum(jnp.mean(v) for v in leaves) / len(leaves)

    return mean_of(aux), mean_of(z)


def pipeline_aux_fold(losses_col):
    """One block's sown MoE losses folded into the SINGLE scalar the
    pipeline stage schedule accumulates (parallel/pipeline.py carries one
    aux carry, later multiplied by MOE_AUX_WEIGHT): aux +
    (MOE_ZLOSS_WEIGHT/MOE_AUX_WEIGHT)·zloss, so each term still lands at
    its own effective weight. Sum (not mean) over this block's leaves —
    the schedule divides by num_layers at the end."""
    # _moe_sown_terms mean-reduces; one block sows one leaf per term, so
    # the mean IS the per-block sum here.
    aux, z = _moe_sown_terms(losses_col)
    return aux + (MOE_ZLOSS_WEIGHT / MOE_AUX_WEIGHT) * z


def moe_token_cross_entropy_loss(model, params, batch, rng=None, *,
                                 diagnostics=False):
    """`token_cross_entropy_loss` (same {tokens, targets, loss_mask?}
    contract) + the MoE auxiliary terms sown by models/moe.py (collection
    "losses"): the Switch load-balance loss (mean over layers, weight
    `MOE_AUX_WEIGHT` — without it top-1 routing collapses onto one
    expert) and the ST-MoE router z-loss (weight `MOE_ZLOSS_WEIGHT`,
    keeps router logits bounded), separated by sown name."""
    logits, mods = _apply_collecting(
        model, params, batch["tokens"], diagnostics=diagnostics,
        mutable=["losses"],
        **_stochastic_kwargs(type(model).__call__, rng))
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["targets"])
    ce, extras = _token_loss_reduce(ce, batch)
    aux, zloss = _moe_sown_terms(mods.get("losses", {}))
    loss = ce + MOE_AUX_WEIGHT * aux + MOE_ZLOSS_WEIGHT * zloss
    return loss, {"loss": loss, "ce": ce, "moe_aux": jnp.float32(aux),
                  "moe_zloss": jnp.float32(zloss),
                  **extras, **_diag_extras(mods, diagnostics)}
