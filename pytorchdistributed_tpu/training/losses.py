"""Loss functions for the built-in task shapes.

The reference's only loss is `F.cross_entropy` / MSE-style regression in the
DDP hot loop (reference ddp_gpus.py:37-42). Losses here are mean-reduced over
the *global* batch: under a sharded batch inside `jit`, the mean lowers to a
local partial sum + `psum` — exactly DDP's gradient-averaging semantics
without a Reducer.

Dropout contract: the Trainer's per-step rng arrives as ``rng``; when set
(training) models that declare ``deterministic`` run with
``deterministic=False`` and a "dropout" rng stream, when None (eval_step)
they run deterministic — so ``dropout_rate > 0`` configs actually drop
units during training and are reproducible at eval.
"""

from __future__ import annotations

import inspect

import jax.numpy as jnp
import optax


def _stochastic_kwargs(target, rng):
    """(kwargs for model.apply) selecting train-mode behavior when ``rng``
    is set: only for methods that take ``deterministic``. That flag now
    gates more than dropout — ResNet's ``deterministic`` switches its
    sync-BN between batch statistics (training; feeds the EMA) and the EMA
    itself (eval), so narrowing this check would silently freeze BN at
    init stats. MLP/toys have no ``deterministic`` and get no kwargs.
    ``target`` is the callable being applied (a Module's __call__ or a
    method like loss_per_position)."""
    if rng is None:
        return {}
    if "deterministic" not in inspect.signature(target).parameters:
        return {}
    return {"deterministic": False, "rngs": {"dropout": rng}}


def mse_loss(model, params, batch, rng=None):
    pred = model.apply(params, batch["x"])
    loss = jnp.mean((pred - batch["y"]) ** 2)
    return loss, {"loss": loss}


def cross_entropy_loss(model, params, batch, rng=None):
    """Image classification: batch = {image, label}. When training (rng
    set), models carrying normalization EMA state (ResNet's "batch_stats")
    refresh it; the updated collection rides the metrics under
    "_collections" — the Trainer pops it and folds it into TrainState
    (the flax mutable-collections train-step pattern)."""
    kwargs = _stochastic_kwargs(type(model).__call__, rng)
    mutable = (["batch_stats"]
               if rng is not None and "batch_stats" in params else [])
    if mutable:
        logits, mods = model.apply(params, batch["image"], mutable=mutable,
                                   **kwargs)
    else:
        logits = model.apply(params, batch["image"], **kwargs)
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["label"]
    ).mean()
    acc = (logits.argmax(-1) == batch["label"]).mean()
    metrics = {"loss": loss, "accuracy": acc}
    if mutable:
        metrics["_collections"] = mods
    return loss, metrics


def token_cross_entropy_loss(model, params, batch, rng=None):
    """LM: batch = {tokens, targets}; optional {loss_mask} for MLM."""
    logits = model.apply(params, batch["tokens"],
                         **_stochastic_kwargs(type(model).__call__, rng))
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["targets"]
    )
    mask = batch.get("loss_mask")
    if mask is not None:
        ce = jnp.where(mask, ce, 0.0)
        loss = ce.sum() / jnp.maximum(mask.sum(), 1)
    else:
        loss = ce.mean()
    return loss, {"loss": loss}


def fused_token_cross_entropy_loss(model, params, batch, rng=None):
    """`token_cross_entropy_loss` through the model's fused chunked-CE head
    (GPT2/Llama `loss_per_position`): the LM head never materializes the
    fp32 ``[batch, seq, vocab]`` logits — ops/fused_ce.py measured the head
    alone at 47 → 123 TFLOP/s on v5e. Same {tokens, targets, loss_mask?}
    contract and the same math (logsumexp CE in fp32) as the unfused loss;
    use for DP/FSDP training of LM models that define `loss_per_position`.
    """
    ce = model.apply(params, batch["tokens"], batch["targets"],
                     method=type(model).loss_per_position,
                     **_stochastic_kwargs(type(model).loss_per_position,
                                          rng))
    mask = batch.get("loss_mask")
    if mask is not None:
        ce = jnp.where(mask, ce, 0.0)
        loss = ce.sum() / jnp.maximum(mask.sum(), 1)
    else:
        loss = ce.mean()
    return loss, {"loss": loss}


MOE_AUX_WEIGHT = 0.01  # Switch Transformer's load-balance coefficient


def moe_token_cross_entropy_loss(model, params, batch, rng=None):
    """`token_cross_entropy_loss` (same {tokens, targets, loss_mask?}
    contract) + the Switch load-balance auxiliary loss sown by models/moe.py
    (collection "losses"). The aux term (mean over layers, weight
    `MOE_AUX_WEIGHT`) pushes the router toward uniform expert utilization;
    without it top-1 routing collapses onto one expert."""
    import jax

    logits, mods = model.apply(params, batch["tokens"], mutable=["losses"],
                               **_stochastic_kwargs(type(model).__call__, rng))
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), batch["targets"])
    mask = batch.get("loss_mask")
    if mask is not None:
        ce = jnp.where(mask, ce, 0.0)
        ce = ce.sum() / jnp.maximum(mask.sum(), 1)
    else:
        ce = ce.mean()
    sown = jax.tree.leaves(mods.get("losses", {}))
    aux = (sum(jnp.mean(v) for v in sown) / max(len(sown), 1)) if sown else 0.0
    loss = ce + MOE_AUX_WEIGHT * aux
    return loss, {"loss": loss, "ce": ce, "moe_aux": jnp.float32(aux)}
