from pytorchdistributed_tpu.training.trainer import Trainer, TrainState  # noqa: F401
from pytorchdistributed_tpu.training.distill import (  # noqa: F401
    DistillTrainer,
    distill_corpus,
    distill_loss,
)
from pytorchdistributed_tpu.training.losses import (  # noqa: F401
    cross_entropy_loss,
    fused_token_cross_entropy_loss,
    moe_token_cross_entropy_loss,
    mse_loss,
    token_cross_entropy_loss,
)
