"""Sharded checkpoint / resume (SURVEY.md §5: the reference has no
`state_dict`/save/load at all; BASELINE's GPT-2 FSDP config requires it).

Built on orbax: each host writes only the param shards it owns (no gather
to host 0 — the torch `state_dict` anti-pattern at pod scale), saves run
async so the train loop isn't blocked, and restore takes abstract
shardings so a checkpoint written on one mesh can resume on another
(re-sharding happens inside orbax/XLA on load).

Integrity (the CheckFreq lesson — a checkpoint you can't trust is worse
than none, because resume=True *prefers* it): after each save commits
(orbax's tmp-dir rename), a ``ptd_manifest.json`` of per-file sizes +
SHA-256 digests is written inside the step directory. ``restore()``
verifies the manifest before reading, and when no explicit step is
pinned it walks back through ``all_steps()`` newest-first, QUARANTINING
corrupt steps (moved to ``<dir>/quarantine/``, never deleted — they are
post-mortem evidence) until a verified checkpoint loads — so a torn or
bit-flipped latest save costs one checkpoint interval, not the job.
Save/restore I/O is retried with bounded backoff (``faults.retry``)
before a transient filesystem error is allowed to kill an incarnation.
Offline: ``python -m pytorchdistributed_tpu.training.checkpoint verify
<dir>`` checks every step of a directory and exits nonzero on corruption.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib
import time
from typing import Any

import jax
import orbax.checkpoint as ocp

from pytorchdistributed_tpu.faults import inject as _inject
from pytorchdistributed_tpu.faults.retry import IO_RETRY, RetryPolicy, retry
from pytorchdistributed_tpu.telemetry.events import (
    EVENT_CKPT_FALLBACK,
    EVENT_CKPT_QUARANTINED,
    EventLog,
)

# the integrity discipline itself (hashing, atomic manifest publish,
# verification verdicts, quarantine moves) is shared with the serving
# layer's persistent-session disk tier (ISSUE 18) via utils/manifest —
# the names below stay importable from here for compatibility
from pytorchdistributed_tpu.utils.manifest import (
    MANIFEST_NAME,
    QUARANTINE_DIR,
    hash_file as _hash_file_impl,
    verify_dir_manifest,
    write_dir_manifest,
)

# Files the manifest must NOT cover: the manifest itself, and orbax's
# step-metadata sidecar — orbax appends commit_timestamp_nsecs to it in
# its own finalize step, which can land after the commit rename our
# flush keys on; hashing a file the writer still legitimately mutates
# would flag healthy checkpoints as corrupt (observed racing once in
# ~10 manual runs). Payload integrity (tensorstore data + tree
# metadata) is fully covered without it.
_MANIFEST_EXCLUDE = frozenset({MANIFEST_NAME, "_CHECKPOINT_METADATA"})


class CheckpointIntegrityError(RuntimeError):
    """An explicitly-requested step failed manifest verification."""


@dataclasses.dataclass(frozen=True)
class StepVerdict:
    """verify_step's answer: ``ok`` is False only on positive evidence of
    corruption; ``verified`` distinguishes a matching manifest from a
    legacy step that has none to check against."""

    step: int
    ok: bool
    verified: bool
    detail: str


def _hash_file(path: pathlib.Path) -> str:
    return _hash_file_impl(path)


class CheckpointManager:
    """``save(step, state)`` / ``restore(abstract_state)`` / ``latest_step()``.

    ``abstract_state``: a pytree of jax.ShapeDtypeStruct with shardings (the
    Trainer passes its state_shardings applied to the current abstract
    state), so restore places every shard directly on its owning device —
    including onto a *different* mesh than the one that saved.
    """

    def __init__(self, directory: str | pathlib.Path, *,
                 max_to_keep: int | None = 3, save_interval_steps: int = 1,
                 retry_policy: RetryPolicy = IO_RETRY):
        self.directory = pathlib.Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )
        self._retry_policy = retry_policy
        # steps whose async save has been started but whose integrity
        # manifest is not yet on disk — flushed when the commit (orbax's
        # tmp-dir rename) is observed
        self._pending_manifest: set[int] = set()
        self._events = EventLog.from_env(int(os.environ.get("RANK", "0")))

    # -- paths -------------------------------------------------------------

    def step_dir(self, step: int) -> pathlib.Path:
        return self.directory / str(step)

    def _manifest_path(self, step: int) -> pathlib.Path:
        return self.step_dir(step) / MANIFEST_NAME

    # -- save ---------------------------------------------------------------

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async sharded save; returns whether a save was started. I/O
        errors at dispatch are retried per the policy; earlier saves that
        have committed since get their manifests flushed here, so a
        long-running loop doesn't defer all integrity work to wait()."""
        self._flush_manifests()
        inj = _inject.active()

        def attempt() -> bool:
            if inj is not None:
                inj.on_io("checkpoint_save", step=step)
            return self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force)

        started = retry(attempt, policy=self._retry_policy,
                        describe=f"checkpoint save step {step}",
                        events=self._events)
        if started:
            self._pending_manifest.add(step)
        return started

    # -- integrity ----------------------------------------------------------

    def _flush_manifests(self, *, all_committed: bool = False) -> None:
        """Write manifests for pending steps whose commit rename has
        landed. ``all_committed``: every pending save is known durable
        (post wait()), so a pending step with no directory was GC'd by
        max_to_keep and is dropped. Manifest writing is rank-0-only (one
        writer per shared directory), and the ckpt_corrupt injection hook
        fires on that SAME rank immediately after its manifest write —
        cross-process ordering between a sibling rank's bit-flip and the
        hash computation is otherwise undefined, and corruption hashed
        INTO the manifest would verify clean, inverting the fault's
        bit-flipped-AFTER-manifest contract."""
        from pytorchdistributed_tpu.runtime import dist

        inj = _inject.active()
        for step in sorted(self._pending_manifest):
            sdir = self.step_dir(step)
            if not sdir.is_dir():
                if all_committed:
                    self._pending_manifest.discard(step)
                continue
            if dist.is_main_process():
                self.write_manifest(step)
                if inj is not None:
                    inj.on_checkpoint_saved(step, sdir)
            self._pending_manifest.discard(step)

    def write_manifest(self, step: int) -> pathlib.Path:
        """Per-file size + SHA-256 manifest for a COMMITTED step,
        written atomically (tmp + rename) beside the data it covers."""
        return write_dir_manifest(self.step_dir(step),
                                  exclude=_MANIFEST_EXCLUDE,
                                  extra={"step": step})

    def verify_step(self, step: int) -> StepVerdict:
        """Check a committed step against its manifest. A step with NO
        manifest passes unverified (legacy saves, or a rank that died
        after commit but before the rank-0 manifest write — orbax's
        commit rename already guarantees the data is whole); a manifest
        that exists and mismatches is positive evidence of corruption."""
        sdir = self.step_dir(step)
        if not sdir.is_dir():
            return StepVerdict(step, False, False, "missing step directory")
        return _verify_step_dir(step, sdir)

    def quarantine(self, step: int, *, reason: str = "") -> pathlib.Path:
        """Move a corrupt step out of orbax's sight (``quarantine/<step>``
        — evidence, not garbage) and refresh the manager's step cache.
        Concurrency-tolerant: on a shared directory every resuming rank
        walks the same fallback chain, so losing the os.replace race to a
        sibling rank is success, not an error."""
        qdir = self.directory / QUARANTINE_DIR
        qdir.mkdir(exist_ok=True)
        dest = qdir / str(step)
        if dest.exists():  # a prior incarnation quarantined this step too
            dest = qdir / f"{step}.{int(time.time() * 1e3)}"
        try:
            os.replace(self.step_dir(step), dest)
        except FileNotFoundError:
            dest = qdir / str(step)  # a sibling rank moved it first
        self._mgr.reload()
        if self._events is not None:
            self._events.emit(EVENT_CKPT_QUARANTINED, step=step,
                              reason=reason[:200], moved_to=str(dest))
        return dest

    # -- restore ------------------------------------------------------------

    def restore(self, abstract_state: Any, *, step: int | None = None) -> Any:
        """Restore ``step`` onto the shardings carried by
        ``abstract_state``. An explicit ``step`` is strict: verification
        failure raises CheckpointIntegrityError (the caller pinned it for
        a reason — silently answering with a different step would lie).
        ``step=None`` walks the verified-fallback chain
        (restore_verified)."""
        if step is None:
            state, _ = self.restore_verified(abstract_state)
            return state
        verdict = self.verify_step(step)
        if not verdict.ok:
            raise CheckpointIntegrityError(
                f"checkpoint step {step} under {self.directory} failed "
                f"verification: {verdict.detail}")
        return self._restore_step(step, abstract_state)

    def restore_verified(self, abstract_state: Any) -> tuple[Any, int]:
        """The fallback chain: newest step first, verify → restore;
        corrupt steps (manifest mismatch, or an unreadable-on-disk
        checkpoint) are quarantined and the walk continues — the last
        verified checkpoint wins. Returns (state, step)."""
        return self._walk_verified(
            lambda step: self._restore_step(step, abstract_state))

    def restore_params(self, *, step: int | None = None) -> tuple[Any, int]:
        """Params-only verified restore — the serving-replica join path
        (ISSUE 10: ``replica_worker`` spec key ``"checkpoint"``). A
        worker knows its model but not the optimizer that trained it,
        so the checkpoint is restored AS SAVED (no abstract tree) and
        only the parameter subtree is returned: a Trainer TrainState
        checkpoint yields its ``.params``, a bare params-tree
        checkpoint yields itself. Same integrity contract as
        ``restore()``: an explicit ``step`` is strict
        (CheckpointIntegrityError on mismatch), ``step=None`` walks the
        verified-fallback chain quarantining corrupt steps. Returns
        ``(params, step)``."""
        if step is not None:
            verdict = self.verify_step(step)
            if not verdict.ok:
                raise CheckpointIntegrityError(
                    f"checkpoint step {step} under {self.directory} "
                    f"failed verification: {verdict.detail}")
            return _params_subtree(self._restore_step_raw(step)), step
        tree, found = self._walk_verified(self._restore_step_raw)
        return _params_subtree(tree), found

    def _walk_verified(self, restore_fn) -> tuple[Any, int]:
        """Newest-first verify → restore → quarantine-and-continue
        walk, shared by the full-state and params-only restores."""
        self._flush_manifests()
        newest = self.latest_step()
        while True:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self.directory} survived "
                    f"verification (see {QUARANTINE_DIR}/)"
                    if newest is not None else
                    f"no checkpoint found under {self.directory}")
            verdict = self.verify_step(step)
            if not verdict.ok:
                self.quarantine(step, reason=verdict.detail)
                continue
            try:
                state = restore_fn(step)
            except Exception as e:  # noqa: BLE001 — filtered below
                if not _is_data_corruption(e):
                    raise
                self.quarantine(step, reason=f"restore failed: {e}"[:200])
                continue
            if step != newest and self._events is not None:
                self._events.emit(EVENT_CKPT_FALLBACK, step=step,
                                  skipped_newest=newest)
            return state, step

    def _restore_step(self, step: int, abstract_state: Any) -> Any:
        inj = _inject.active()

        def attempt():
            if inj is not None:
                inj.on_io("checkpoint_restore", step=step)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract_state))

        return retry(attempt, policy=self._retry_policy,
                     describe=f"checkpoint restore step {step}",
                     events=self._events)

    def _restore_step_raw(self, step: int) -> Any:
        """Restore a step AS SAVED (no abstract tree): leaves land as
        host arrays with the checkpoint's own structure — the
        params-only path, which re-commits to device on first use."""
        inj = _inject.active()

        def attempt():
            if inj is not None:
                inj.on_io("checkpoint_restore", step=step)
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore())

        return retry(attempt, policy=self._retry_policy,
                     describe=f"checkpoint restore step {step}",
                     events=self._events)

    # -- bookkeeping ---------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait(self) -> None:
        """Block until in-flight async saves are durable (call before exit
        and in tests); manifests for the committed saves are written here,
        so after wait() returns the newest checkpoint is both durable AND
        verifiable — the preemption handler's contract."""
        self._mgr.wait_until_finished()
        self._flush_manifests(all_committed=True)

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()


def _params_subtree(tree: Any) -> Any:
    """The parameter subtree of a restored-as-saved checkpoint: a
    Trainer TrainState (dict with params + opt_state once orbax
    round-trips the PyTreeNode) yields its params; anything else is
    assumed to BE a params tree."""
    if isinstance(tree, dict) and "params" in tree and "opt_state" in tree:
        return tree["params"]
    return tree


def _is_data_corruption(e: BaseException) -> bool:
    """Restore exceptions that indicate on-disk damage (walk back) vs
    caller error like a mismatched abstract tree (re-raise). Orbax
    surfaces tensorstore corruption as ValueError with status-code text;
    OSError covers torn metadata reads."""
    if isinstance(e, (OSError, json.JSONDecodeError)):
        return True
    text = str(e)
    return any(tag in text for tag in
               ("DATA_LOSS", "NOT_FOUND", "FAILED_PRECONDITION",
                "Error reading", "Error opening"))


def abstract_state_like(state, state_shardings):
    """ShapeDtypeStruct tree carrying the target shardings, for restore."""
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state, state_shardings)


def verify_directory(directory: str | pathlib.Path) -> list[StepVerdict]:
    """Offline integrity sweep of every step under ``directory`` (the
    CLI's engine; no device work, no orbax restore — manifest checks
    only)."""
    directory = pathlib.Path(directory)
    verdicts = []
    for entry in sorted(directory.iterdir() if directory.is_dir() else [],
                        key=lambda p: (len(p.name), p.name)):
        if entry.is_dir() and entry.name.isdigit():
            verdicts.append(_verify_step_dir(int(entry.name), entry))
    return verdicts


def _verify_step_dir(step: int, sdir: pathlib.Path) -> StepVerdict:
    """Manifest check against one step directory (shared by
    CheckpointManager.verify_step's logic and the standalone CLI)."""
    ok, verified, detail = verify_dir_manifest(sdir)
    return StepVerdict(step, ok, verified, detail)


def main(argv=None) -> int:
    """``python -m pytorchdistributed_tpu.training.checkpoint verify
    <dir>``: offline integrity report, exit 1 when any step is corrupt
    (unverified legacy steps report but do not fail)."""
    import argparse

    parser = argparse.ArgumentParser(
        "pytorchdistributed_tpu.training.checkpoint")
    sub = parser.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("verify",
                       help="check every step's integrity manifest")
    v.add_argument("directory")
    v.add_argument("--strict", action="store_true",
                   help="also fail on steps with no manifest to check")
    args = parser.parse_args(argv)

    verdicts = verify_directory(args.directory)
    if not verdicts:
        print(f"no checkpoint steps under {args.directory}")
        return 1
    bad = 0
    for vd in verdicts:
        status = ("OK" if vd.ok and vd.verified
                  else "UNVERIFIED" if vd.ok else "CORRUPT")
        if not vd.ok or (args.strict and not vd.verified):
            bad += 1
        print(f"step {vd.step:>8}  {status:<10}  {vd.detail}")
    print(f"{len(verdicts)} step(s), {bad} bad")
    return 1 if bad else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
