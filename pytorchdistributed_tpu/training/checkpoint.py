"""Sharded checkpoint / resume (SURVEY.md §5: the reference has no
`state_dict`/save/load at all; BASELINE's GPT-2 FSDP config requires it).

Built on orbax: each host writes only the param shards it owns (no gather
to host 0 — the torch `state_dict` anti-pattern at pod scale), saves run
async so the train loop isn't blocked, and restore takes abstract
shardings so a checkpoint written on one mesh can resume on another
(re-sharding happens inside orbax/XLA on load).
"""

from __future__ import annotations

import pathlib
from typing import Any

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """``save(step, state)`` / ``restore(abstract_state)`` / ``latest_step()``.

    ``abstract_state``: a pytree of jax.ShapeDtypeStruct with shardings (the
    Trainer passes its state_shardings applied to the current abstract
    state), so restore places every shard directly on its owning device —
    including onto a *different* mesh than the one that saved.
    """

    def __init__(self, directory: str | pathlib.Path, *,
                 max_to_keep: int | None = 3, save_interval_steps: int = 1):
        self.directory = pathlib.Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Async sharded save; returns whether a save was started."""
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)

    def restore(self, abstract_state: Any, *, step: int | None = None) -> Any:
        """Restore ``step`` (default: latest) onto the shardings carried by
        ``abstract_state``."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait(self) -> None:
        """Block until in-flight async saves are durable (call before exit
        and in tests)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()


def abstract_state_like(state, state_shardings):
    """ShapeDtypeStruct tree carrying the target shardings, for restore."""
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state, state_shardings)
