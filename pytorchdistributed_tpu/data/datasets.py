"""Hermetic synthetic datasets (the reference's test substrate).

The reference verifies everything with random tensors so no download is ever
needed (SURVEY.md §4): `MyTrainDataset` of 2048 × (rand(20), rand(1)) pairs
(reference ddp_gpus.py:57-66) and `generate_random_data()` ImageNet-shaped
batches (reference 03_model_parallel.ipynb cell 7). Same policy here.

TPU-first design note: datasets are array-backed and indexed with *vectors* of
indices, so a whole batch is one fancy-indexing gather on the host — no
per-sample Python loop, no collate step.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np


class ArrayDataset:
    """Map-style dataset over a dict of equally-sized leading-dim arrays.

    ``ds[indices]`` with an integer vector returns the batch dict directly.
    """

    def __init__(self, arrays: Mapping[str, np.ndarray]):
        if not arrays:
            raise ValueError("arrays must be non-empty")
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"mismatched leading dims: {sizes}")
        self.arrays = dict(arrays)
        self._size = next(iter(sizes.values()))

    def __len__(self) -> int:
        return self._size

    def __getitem__(self, idx) -> dict[str, np.ndarray]:
        if isinstance(idx, np.ndarray) and idx.ndim == 1 \
                and np.issubdtype(idx.dtype, np.integer):
            # batch gather — the loader's hot loop; native multithreaded
            # row copy when csrc/ is built (GIL released), numpy otherwise
            from pytorchdistributed_tpu import _native

            return {k: _native.gather(v, idx) for k, v in self.arrays.items()}
        return {k: v[idx] for k, v in self.arrays.items()}


class SyntheticRegressionDataset(ArrayDataset):
    """The reference's ``MyTrainDataset``: pairs of (rand(in), rand(out))
    (reference ddp_gpus.py:57-66; defaults 2048 × (20 → 1))."""

    def __init__(self, size: int = 2048, in_dim: int = 20, out_dim: int = 1,
                 seed: int = 0):
        rng = np.random.default_rng(seed)
        super().__init__({
            "x": rng.random((size, in_dim), dtype=np.float32),
            "y": rng.random((size, out_dim), dtype=np.float32),
        })


class SyntheticImageDataset(ArrayDataset):
    """ImageNet-shaped random data (reference 03_model_parallel.ipynb cell 7:
    3×128×128, 1000 classes) — stored NHWC, the TPU-native image layout."""

    def __init__(self, size: int = 1024, image_size: int = 128,
                 channels: int = 3, num_classes: int = 1000, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.num_classes = num_classes
        super().__init__({
            "image": rng.standard_normal(
                (size, image_size, image_size, channels)).astype(np.float32),
            "label": rng.integers(0, num_classes, (size,), dtype=np.int32),
        })


class SyntheticTokenDataset(ArrayDataset):
    """Random token sequences for LM / MLM configs (BASELINE.json configs
    3-4). ``tokens`` are inputs; ``targets`` are tokens shifted by one for
    causal LM training."""

    def __init__(self, size: int = 1024, seq_len: int = 128,
                 vocab_size: int = 32000, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.vocab_size = vocab_size
        toks = rng.integers(0, vocab_size, (size, seq_len + 1), dtype=np.int32)
        super().__init__({
            "tokens": toks[:, :-1],
            "targets": toks[:, 1:],
        })


class MLMDataset:
    """Dynamic masked-LM view over any token dataset (BERT's objective —
    the reference never reaches it; BASELINE config[2] demands it).

    Wraps a dataset yielding ``{"tokens", ...}`` and applies BERT's dynamic
    masking (RoBERTa-style), deterministic **per sample**: sample ``i``'s
    mask depends only on ``(seed, i)``, never on which other indices share
    the fetch — so ``ds[[0, 1]]`` masks sample 0 exactly like ``ds[0]``
    and MLM val losses stay comparable across batch sizes and replica
    counts (ADVICE r2). Of ``mask_rate`` selected positions, 80% become
    ``mask_id`` (default: vocab_size-1, reserved by convention), 10% a
    random token, 10% unchanged. Emits the BERT batch contract: tokens
    (corrupted), targets (originals), loss_mask (selected positions).
    """

    def __init__(self, base, vocab_size: int, *, mask_rate: float = 0.15,
                 mask_id: int | None = None, seed: int = 0):
        self.base = base
        self.vocab_size = vocab_size
        self.mask_rate = mask_rate
        self.mask_id = vocab_size - 1 if mask_id is None else mask_id
        self.seed = seed

    def __len__(self) -> int:
        return len(self.base)

    def __getitem__(self, idx) -> dict[str, np.ndarray]:
        batch = self.base[idx]
        tokens = np.asarray(batch["tokens"], np.int32)
        flat = np.atleast_1d(np.asarray(idx)).astype(np.int64)
        # negatives index fine in the base; map them to their positive
        # aliases for the rng entropy (SeedSequence rejects negatives, and
        # ds[-1] must mask identically to ds[len-1])
        flat = flat % max(len(self), 1)
        # one independent stream PER index: r and the replacement draws for
        # row i come from default_rng([seed, i]) alone, so a sample's mask
        # is identical no matter how it is batched
        seq_shape = tokens.shape[-1:] if tokens.ndim else tokens.shape
        r_rows, rand_rows = [], []
        for i in flat.tolist():
            row_rng = np.random.default_rng([self.seed, i])
            r_rows.append(row_rng.random(seq_shape))
            rand_rows.append(row_rng.integers(
                0, self.vocab_size - 1, seq_shape, dtype=np.int32))
        r = np.stack(r_rows).reshape(tokens.shape)
        rand = np.stack(rand_rows).reshape(tokens.shape)
        selected = r < self.mask_rate
        # split the selected mass 80/10/10 by where r falls inside it
        to_mask = r < self.mask_rate * 0.8
        to_rand = (r >= self.mask_rate * 0.8) & (r < self.mask_rate * 0.9)
        corrupted = np.where(to_mask, self.mask_id, tokens)
        # random replacements never emit mask_id (draw over vocab-1 ids,
        # shift past the hole)
        rand = rand + (rand >= self.mask_id)
        corrupted = np.where(to_rand, rand, corrupted)
        return {"tokens": corrupted.astype(np.int32),
                "targets": tokens,
                "loss_mask": selected.astype(np.int32)}
