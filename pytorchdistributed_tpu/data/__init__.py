from pytorchdistributed_tpu.data.sampler import ShardedSampler  # noqa: F401
from pytorchdistributed_tpu.data.loader import DataLoader, prefetch_to_device  # noqa: F401
from pytorchdistributed_tpu.data.datasets import (  # noqa: F401
    ArrayDataset,
    SyntheticRegressionDataset,
    SyntheticImageDataset,
    SyntheticTokenDataset,
    MLMDataset,
)
from pytorchdistributed_tpu.data.files import (  # noqa: F401
    MappedImageDataset,
    MappedTokenDataset,
    load_cifar10,
    load_image_dir,
    load_tokens,
)
