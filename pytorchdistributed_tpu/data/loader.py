"""Batched, device-fed data loading.

Replaces the reference's `DataLoader(..., pin_memory=True)` + per-batch
`.to(gpu_id)` copies (reference ddp_gpus.py:71-76, 49-50) with the TPU
pattern: the host assembles its process-local batch with one vectorized
gather, and `shard_batch` turns it into a *global* jax.Array laid out by a
`NamedSharding` — `jax.device_put` single-process, or
`jax.make_array_from_process_local_data` on a multi-host pod. A small
double-buffered prefetcher overlaps host gather + H2D DMA with device compute
(the role `pin_memory=True` played on CUDA).
"""

from __future__ import annotations

import collections
import contextlib
from typing import Iterator

import jax
import numpy as np

from pytorchdistributed_tpu.data.sampler import ShardedSampler
from pytorchdistributed_tpu.faults import inject as _inject


class DataLoader:
    """Iterates per-process batches of a map-style array dataset.

    ``batch_size`` is the per-process batch (matching torch's per-rank
    meaning); the global batch is ``batch_size * num_replicas``. Iteration
    order is deterministic in (seed, epoch) across processes.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        num_replicas: int | None = None,
        rank: int | None = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = ShardedSampler(
            len(dataset),
            num_replicas if num_replicas is not None else jax.process_count(),
            rank if rank is not None else jax.process_index(),
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
        )
        self.drop_last = drop_last

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        n = self.sampler.num_samples
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        indices = self.sampler.local_indices()
        nbatches = len(self)
        # Fault-injection hook (faults/inject.py, None without a
        # PTD_FAULTS plan): slow_io makes this rank's batch assembly
        # straggle, io_err crashes it mid-epoch — the loader-side faults
        # the chaos suite drives through run.py.
        inj = _inject.active()
        for b in range(nbatches):
            if inj is not None:
                inj.on_io("data_batch")
            batch_idx = indices[b * self.batch_size : (b + 1) * self.batch_size]
            yield self.dataset[batch_idx]


def shard_batch(batch: dict[str, np.ndarray], sharding) -> dict[str, jax.Array]:
    """Assemble the global device-laid-out batch from this process's shard.

    ``sharding`` may be one NamedSharding for every leaf, or a callable
    ``leaf -> NamedSharding`` (rank-aware per-leaf layout,
    mesh.batch_leaf_sharding)."""
    pick = sharding if callable(sharding) else (lambda _: sharding)
    if jax.process_count() > 1:
        return {
            k: jax.make_array_from_process_local_data(pick(v), v)
            for k, v in batch.items()
        }
    return {k: jax.device_put(v, pick(v)) for k, v in batch.items()}


def prefetch_to_device(
    iterator: Iterator[dict[str, np.ndarray]],
    sharding,
    size: int = 2,
    tracer=None,
) -> Iterator[dict[str, jax.Array]]:
    """Double-buffer: keep ``size`` batches in flight on device so the H2D
    transfer of batch k+1 overlaps the compute of batch k. ``tracer`` (a
    telemetry.SpanTracer) records each shard/H2D handoff as an
    "h2d_transfer" host span — note the span covers the *dispatch* of the
    transfer; the DMA itself overlaps compute by design.

    ``size`` is the configurable depth (Trainer(prefetch=N) /
    PTD_PREFETCH): 2 is the committed double-buffer default; deeper
    queues buy jitter tolerance at ``size`` batches of extra device
    memory; ``size=0`` degrades to fully synchronous transfer — each
    batch is sharded and handed over immediately, nothing queued ahead
    (the debugging/memory-floor mode, and the semantics every positive
    depth reduces to at iterator exhaustion)."""
    if size < 0:
        raise ValueError(f"prefetch size must be >= 0, got {size}")
    queue: collections.deque = collections.deque()
    for batch in iterator:
        cm = (tracer.span("h2d_transfer") if tracer is not None
              else contextlib.nullcontext())
        with cm:
            queue.append(shard_batch(batch, sharding))
        if len(queue) >= size:  # size 0: always — fully synchronous
            yield queue.popleft()
    while queue:
        yield queue.popleft()
