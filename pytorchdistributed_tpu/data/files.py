"""On-disk datasets — the real-data path behind the BASELINE configs
(ResNet-18/**CIFAR-10**, ResNet-50/**ImageNet**), which the reference never
has (its data is always synthetic, ddp_gpus.py:57-66). Zero-copy design:

  * ``.npy`` files open with ``np.load(mmap_mode="r")`` — the OS page cache
    is the shuffle buffer, nothing is loaded up front;
  * batch assembly is the same vectorized row-gather as the synthetic path
    (`ArrayDataset.__getitem__` → `_native.gather`, the multithreaded C++
    copy in csrc/ptd_host.cc) — on 224×224 ImageNet rows (~600KB each) this
    is where the native loader earns its keep;
  * no downloads: if the files are absent the callers fall back to
    synthetic data (the environment has no egress; provisioning data is the
    operator's job).

Layouts understood:
  * ``<root>/<split>_images.npy`` + ``<root>/<split>_labels.npy`` — the
    generic array-file convention (`MappedImageDataset`);
  * ``<root>/cifar-10-batches-py/`` — the standard CIFAR-10 python pickle
    distribution (`load_cifar10`), converted once to the ``.npy`` pair
    beside it and memory-mapped thereafter.
"""

from __future__ import annotations

import pathlib
import pickle

import numpy as np

from pytorchdistributed_tpu.data.datasets import ArrayDataset
from pytorchdistributed_tpu.faults import inject as _inject
from pytorchdistributed_tpu.faults.retry import IO_RETRY, retry


def _read(what: str, fn):
    """File-read thunk hardened per SURVEY.md §5: the fault-injection
    hook fires first (``slow_io`` / ``io_err`` specs), then the read runs
    under bounded-backoff retry — a transient filesystem error (evicted
    page, NFS hiccup, injected OSError) costs delays and telemetry
    events, not the training incarnation. Permanent errors still raise
    after the policy's attempts."""
    inj = _inject.active()

    def attempt():
        if inj is not None:
            inj.on_io(what)
        return fn()

    return retry(attempt, policy=IO_RETRY, describe=what,
                 events=inj.events if inj is not None else None)


class MappedImageDataset(ArrayDataset):
    """Memory-mapped ``{split}_images.npy`` / ``{split}_labels.npy`` pair.

    Images may be stored uint8 (the compact on-disk form); they are
    normalized to float32 per-batch AFTER the gather, so the mmap stays
    byte-for-byte the file and the page cache is shared across processes.
    """

    def __init__(self, root: str | pathlib.Path, split: str = "train",
                 mean: float = 0.0, scale: float = 1 / 255.0):
        root = pathlib.Path(root)
        images = _read(f"{split}_images.npy", lambda: np.load(
            root / f"{split}_images.npy", mmap_mode="r"))
        labels = _read(f"{split}_labels.npy", lambda: np.load(
            root / f"{split}_labels.npy", mmap_mode="r"))
        self.num_classes = int(labels.max()) + 1
        self._mean, self._scale = mean, scale
        super().__init__({"image": images, "label": labels})

    def __getitem__(self, idx):
        batch = super().__getitem__(idx)
        img = batch["image"]
        if img.dtype != np.float32:
            img = (img.astype(np.float32) - self._mean) * self._scale
        return {"image": img,
                "label": np.asarray(batch["label"], np.int32)}


def _convert_cifar10(batches_dir: pathlib.Path, split: str) -> None:
    """One-time conversion of the pickle batches to the ``.npy`` pair
    (written beside ``cifar-10-batches-py/``): NCHW-packed rows → NHWC
    uint8, the TPU-native image layout."""
    names = ([f"data_batch_{i}" for i in range(1, 6)]
             if split == "train" else ["test_batch"])
    images, labels = [], []

    def read_pickle(path):
        with open(path, "rb") as f:
            return pickle.load(f, encoding="bytes")

    for name in names:
        d = _read(name, lambda: read_pickle(batches_dir / name))
        images.append(np.asarray(d[b"data"], np.uint8)
                      .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        labels.append(np.asarray(d[b"labels"], np.int32))
    root = batches_dir.parent
    np.save(root / f"{split}_images.npy", np.concatenate(images))
    np.save(root / f"{split}_labels.npy", np.concatenate(labels))


def load_cifar10(root: str | pathlib.Path,
                 split: str = "train") -> MappedImageDataset | None:
    """CIFAR-10 from ``<root>/cifar-10-batches-py`` (or an already-converted
    ``.npy`` pair under ``<root>``); None when neither exists — callers fall
    back to synthetic data."""
    root = pathlib.Path(root)
    if not (root / f"{split}_images.npy").exists():
        batches = root / "cifar-10-batches-py"
        if not batches.exists():
            return None
        _convert_cifar10(batches, split)
    return MappedImageDataset(root, split)


def load_image_dir(root: str | pathlib.Path,
                   split: str = "train") -> MappedImageDataset | None:
    """Generic array-file dataset (the ImageNet-config path): the
    ``{split}_images.npy``/``{split}_labels.npy`` convention, else None."""
    root = pathlib.Path(root)
    if not (root / f"{split}_images.npy").exists():
        return None
    return MappedImageDataset(root, split)


class MappedTokenDataset(ArrayDataset):
    """Memory-mapped pre-tokenized LM corpus: ``<root>/<split>_tokens.npy``,
    either a 1-D token stream (windowed into non-overlapping ``seq_len+1``
    chunks, causal next-token targets) or an already-windowed 2-D
    ``[n, >=seq_len+1]`` array.

    The gather fetches whole contiguous rows (a column-sliced mmap view
    would silently bypass the native multithreaded gather, which requires
    C-contiguous sources); tokens/targets are sliced from the gathered
    batch. Token-id bounds (the vocab check, and a negative-id guard — a
    ``-1``-padded corpus would otherwise wrap through the embedding/CE
    gathers into finite-but-wrong losses) are scanned ONCE and cached in a
    ``<split>_tokens.meta.json`` sidecar, so steady-state construction
    touches no corpus pages."""

    def __init__(self, root: str | pathlib.Path, seq_len: int,
                 split: str = "train"):
        root = pathlib.Path(root)
        path = root / f"{split}_tokens.npy"
        arr = _read(f"{split}_tokens.npy",
                    lambda: np.load(path, mmap_mode="r"))
        # Bounds come from the UN-windowed on-disk array: a 1-D stream is
        # truncated to a seq_len multiple below, so seq_len-dependent bounds
        # would let a cached scan from one seq_len skip tokens (e.g. a
        # trailing -1 pad) that another seq_len exposes.
        lo, hi = self._token_bounds(path, arr)
        if lo < 0:
            raise ValueError(
                f"{split}_tokens.npy contains negative token ids "
                f"(min {lo}); pad/ignore ids must be remapped before "
                f"training")
        self.vocab_size = hi + 1
        if arr.ndim == 1:
            n = arr.shape[0] // (seq_len + 1)
            if n == 0:
                raise ValueError(
                    f"{split}_tokens.npy holds {arr.shape[0]} tokens — "
                    f"fewer than one seq_len+1={seq_len + 1} window")
            arr = arr[: n * (seq_len + 1)].reshape(n, seq_len + 1)
        elif arr.shape[1] < seq_len + 1:
            raise ValueError(
                f"{split}_tokens.npy rows have {arr.shape[1]} tokens; "
                f"need seq_len+1={seq_len + 1}")
        self._seq_len = seq_len
        super().__init__({"chunk": arr})

    @staticmethod
    def _token_bounds(path: pathlib.Path, arr) -> tuple[int, int]:
        import json

        meta = path.with_name(path.stem + ".meta.json")
        st = path.stat()
        # "v": 2 = bounds scanned over the UN-windowed array; older or
        # unversioned sidecars (seq_len-dependent bounds) must rescan.
        key = {"v": 2, "size": st.st_size, "mtime_ns": st.st_mtime_ns}
        try:  # corrupt / mid-write / non-dict sidecar -> rescan
            cached = json.loads(meta.read_text())
            if (isinstance(cached, dict)
                    and all(cached.get(k) == v for k, v in key.items())):
                return cached["min"], cached["max"]
        except (OSError, ValueError, KeyError):
            pass
        lo, hi = int(arr.min()), int(arr.max())
        try:  # best-effort cache via temp+rename (atomic for readers);
            # a read-only data dir just rescans next time
            tmp = meta.with_name(meta.name + ".tmp")
            tmp.write_text(json.dumps({**key, "min": lo, "max": hi}))
            tmp.replace(meta)
        except OSError:
            pass
        return lo, hi

    def __getitem__(self, idx):
        chunk = super().__getitem__(idx)["chunk"]
        s = self._seq_len
        return {"tokens": np.asarray(chunk[:, :s], np.int32),
                "targets": np.asarray(chunk[:, 1:s + 1], np.int32)}


def load_tokens(root: str | pathlib.Path, seq_len: int,
                split: str = "train") -> MappedTokenDataset | None:
    """``<root>/<split>_tokens.npy`` when present, else None — the LM
    analog of load_image_dir (GPT-2/Llama/BERT presets with --data_dir)."""
    root = pathlib.Path(root)
    if not (root / f"{split}_tokens.npy").exists():
        return None
    return MappedTokenDataset(root, seq_len, split)
