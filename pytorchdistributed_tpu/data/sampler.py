"""Deterministic sharded sampling — the `DistributedSampler` contract.

The reference relies on `DistributedSampler(train_dataset)` for disjoint
per-rank shards ("没有任何 overlapping samples 各个 gpu 之间", reference
ddp_gpus.py:75-76) and `sampler.set_epoch(epoch)` for a different shuffle
every epoch (reference ddp_gpus.py:47). This module provides the same
contract, TPU-first:

  * shuffling is host-side numpy, seeded with ``seed·1_000_003 + epoch``
    (stateless in (seed, epoch) with no cross-seed/epoch collisions,
    identical on every process — a requirement for SPMD, where each host
    must compute the SAME global permutation and then slice out its shard;
    no device work for what is index bookkeeping);
  * shards are contiguous slices of the permuted index list, so a host feeding
    N local devices can take one contiguous run and let `jax.device_put` with
    a sharding split it further;
  * `drop_last` or pad-to-divisible semantics match torch's
    (pad repeats the head of the permutation, like DistributedSampler).
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    """Yields the index shard for ``rank`` out of ``num_replicas``.

    Deterministic in (seed, epoch): every process computes the same global
    permutation (numpy RNG seeded with ``seed + epoch``) and takes a disjoint
    contiguous slice. With ``drop_last=False`` the index list is padded by
    wrapping around so every replica gets the same count.
    """

    def __init__(
        self,
        dataset_size: int,
        num_replicas: int,
        rank: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range [0, {num_replicas})")
        if dataset_size <= 0:
            raise ValueError("dataset_size must be positive")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        if drop_last:
            self.num_samples = dataset_size // num_replicas
        else:
            self.num_samples = -(-dataset_size // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Re-key the shuffle (reference ddp_gpus.py:47)."""
        self.epoch = epoch

    def _global_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed * 1_000_003 + self.epoch)
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        if self.drop_last:
            indices = indices[: self.total_size]
        elif self.total_size > self.dataset_size:
            pad = self.total_size - self.dataset_size
            indices = np.concatenate([indices, indices[:pad]])
        return indices

    def local_indices(self) -> np.ndarray:
        """This replica's contiguous shard of the global permutation."""
        start = self.rank * self.num_samples
        return self._global_indices()[start : start + self.num_samples]

    def valid_mask(self) -> np.ndarray:
        """True where ``local_indices()[i]`` is a real sample, False where it
        is wrap-around padding (with drop_last=False the global index list is
        padded past ``dataset_size`` by repeating the permutation head, and
        the pad tail lands in the last replica's shard). `Trainer.evaluate`
        turns this into per-sample weights so padded duplicates don't skew
        eval means (ADVICE r2)."""
        start = self.rank * self.num_samples
        return np.arange(start, start + self.num_samples) < self.dataset_size

    def __iter__(self):
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples
