"""tpu-distributed: a TPU-native distributed training framework.

Built from scratch in JAX/XLA (pjit, shard_map, Pallas) to provide the full
capability surface exercised by the reference tutorial repo
JoeyOL/PytorchDistributed (see SURVEY.md): process-group initialization and
per-chip launching, data-parallel training with deterministic sharded sampling
and gradient all-reduce over ICI, tensor/model sharding, micro-batched pipeline
parallelism (GPipe and 1F1B schedules), FSDP-style parameter+optimizer sharding
with bf16 and activation checkpointing, sequence/context parallelism (ring
attention, Ulysses) for long context, Switch-MoE expert parallelism over the
expert axis, memory-budgeted auto placement (the device_map="auto" analog),
a model zoo (GPT-2, Llama with RoPE/SwiGLU/GQA, BERT, ViT, ResNet) on one
shared Transformer core, KV-cache autoregressive generation
(inference.generate), and a continuous-batching serving engine over a
slot-based KV cache (serving.ServingEngine).

Design stance (SURVEY.md §7): the reference's wrapper classes
(DataParallel/DDP, reference ddp_gpus.py:35) become *sharding-spec choices over
a single jitted train step* on a `jax.sharding.Mesh`; collectives are XLA HLO
ops over ICI/DCN rather than a userspace NCCL; pipeline schedules remain real
framework code.
"""

__version__ = "0.1.0"

# Re-assert the standard JAX_PLATFORMS env contract: some environments
# (e.g. a sitecustomize registering a TPU-tunnel plugin) import jax before
# user code runs, baking their platform choice into jax.config so the env
# var the user set is silently ignored. Harmless when no backend is
# initialized yet; no-op otherwise.
import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

# Backfill current-stable jax API names (jax.set_mesh / jax.shard_map /
# jax.typeof / jax.sharding.get_abstract_mesh) on images pinning an older
# jax — strict no-op when the running jax already provides them.
from pytorchdistributed_tpu import _jax_compat as _jax_compat

_jax_compat.install()

from pytorchdistributed_tpu.runtime.mesh import (  # noqa: F401
    Axis,
    MeshConfig,
    create_mesh,
    local_mesh,
)
from pytorchdistributed_tpu.runtime.dist import (  # noqa: F401
    init_process_group,
    destroy_process_group,
    get_rank,
    get_world_size,
    is_initialized,
)
from pytorchdistributed_tpu.inference import (  # noqa: F401
    generate,
    generate_bucketed,
    generate_speculative,
    truncated_draft,
)
