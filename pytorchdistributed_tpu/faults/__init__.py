"""Fault injection + retry/backoff — the harness that proves SURVEY.md
§5's "failure detection / elastic recovery" actually recovers.

``inject``: a deterministic FaultPlan (``PTD_FAULTS`` env spec /
``run.py --faults``) fired through hooks in the Trainer step loop, the
data loaders and the checkpoint save path. ``retry``: bounded
exponential-backoff retry wrapped around checkpoint and data-file I/O.
Both emit TelemetryEvents so every injection and every retry is durable
in the run record.
"""

from pytorchdistributed_tpu.faults.chaos import (  # noqa: F401
    ChaosSchedule,
    recovery_table,
)
from pytorchdistributed_tpu.faults.inject import (  # noqa: F401
    CRASH_EXIT_CODE,
    EXIT_PREEMPTED,
    FAULTS_ENV,
    FAULTS_STATE_ENV,
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from pytorchdistributed_tpu.faults.retry import (  # noqa: F401
    IO_RETRY,
    RetryPolicy,
    retry,
    retryable,
)
