"""Rate-based fault schedules + wire-level mangling (ISSUE 19).

The base ``FaultInjector`` fires one-shot ``@tick=T`` specs — fine for
"prove the watchdog catches ONE hang", useless for a soak, where faults
must keep arriving for minutes with random overlap. ``ChaosSchedule``
extends it into a *process*: every serving/wire spec may carry

  * ``rate=R`` — a Poisson process at R events/sec over the schedule's
    clock (wall or fake): each consult fires with probability
    ``1 - exp(-R * dt)`` for the elapsed ``dt``;
  * ``period=P`` — deterministic firings every P seconds (elapsed time
    is accumulated, so a slow tick can fire multiple times);
  * ``burst=B`` — each firing claims B victims instead of one;
  * ``replica=I`` — target replica I; omitted → a seeded-RNG choice
    from the replicas the schedule has seen this tick.

One-shot ``@tick=T`` specs still work (``super().on_serving_tick``
handles them, markers and all), so a plan can mix
``replica_crash@tick=40; replica_hang@rate=0.05; wire_torn@rate=0.02``.
Determinism: all randomness flows from the constructor seed plus the
injected clock, so a soak with ``FakeClock`` replays bit-identically.

Wire faults never reach ``on_serving_tick`` — the router's
``SubprocessReplica`` consults ``mangle_recv`` on every response line
instead, and the schedule corrupts/tears/delays/drops it there. The
router's job (serving/router.py) is to survive whatever this returns:
a mangled line is a protocol fault → quarantine, a dropped line is
silence → the per-op timeout machinery.

``recovery_table`` is the read side: given the router's telemetry event
stream it matches each injection to its detection and recovery events
and reports per-fault-class MTTR percentiles — the number the soak
stamps into BENCH_soak.json.
"""

from __future__ import annotations

import math
import random
import time

from pytorchdistributed_tpu.faults.inject import (
    _SERVING_KINDS,
    _WIRE_KINDS,
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from pytorchdistributed_tpu.telemetry.events import EventLog

__all__ = ["ChaosSchedule", "recovery_table"]


class ChaosSchedule(FaultInjector):
    """A FaultInjector whose serving/wire specs fire as rate-based
    processes over an injected clock.

    The router consults ``on_serving_tick(tick, replica)`` once per
    replica per tick (exactly the base-class contract) and
    ``mangle_recv(replica, line)`` once per received wire line. Rate
    decisions are made once per (spec, tick): the first consult of a
    tick draws how many victims each spec claims and which replicas
    they are; later consults of the same tick just collect their
    verdicts. Targeted specs (``replica=I``) only ever hit I; random
    ones draw from the replicas seen on the *previous* consult round,
    so the victim pool tracks the live fleet.
    """

    #: Routers check this to know the injector wants per-tick consults
    #: even for subprocess replicas (whose workers run their own base
    #: injector for one-shot specs) — rate decisions live router-side.
    rate_based = True

    def __init__(self, plan: FaultPlan | str, *, seed: int = 0,
                 rank: int = 0, state_dir: str | None = None,
                 events: EventLog | None = None,
                 clock=time.monotonic):
        if isinstance(plan, str):
            plan = FaultPlan.parse(plan)
        super().__init__(plan, rank=rank, state_dir=state_dir,
                         events=events, seed=seed)
        self._clock = clock
        self._chaos_rng = random.Random((seed, 0xC4A05, len(plan.specs))
                                        .__hash__())
        #: last decision time per spec index (None = epoch unset: the
        #: first consult only anchors the clock, nothing fires at t=0)
        self._spec_t: list[float | None] = [None] * len(plan.specs)
        self._acc = [0.0] * len(plan.specs)   # period accumulator
        #: wire rate/period state is PER (spec, replica): each pipe is
        #: its own Poisson process anchored at its own first line, so a
        #: replica whose first response lands late (sequential warmups
        #: take tens of seconds each) doesn't inherit a huge dt and a
        #: near-certain fault from a sibling's anchor
        self._wire_t: dict[tuple[int, int], float] = {}
        self._wire_acc: dict[tuple[int, int], float] = {}
        self._known: set[int] = set()         # replicas seen this tick
        self._prev_known: set[int] = set()
        self._decided_tick: int | None = None
        self._decisions: dict[int, FaultSpec] = {}  # replica -> spec
        #: append-only log of every firing (serving AND wire), for the
        #: soak report: {kind, replica, tick, time}
        self.injected: list[dict] = []

    # -- rate machinery ----------------------------------------------------

    def _draw_fires(self, i: int, spec: FaultSpec, now: float) -> int:
        """How many times spec i fires for the elapsed interval ending
        at ``now``. First consult anchors the epoch and returns 0."""
        last = self._spec_t[i]
        self._spec_t[i] = now
        if last is None:
            return 0
        dt = max(0.0, now - last)
        fires = 0
        if spec.rate is not None:
            # P(at least one Poisson arrival in dt); one firing per
            # consult interval is plenty at soak rates, and burst=
            # scales the blast radius when it isn't
            if self._chaos_rng.random() < -math.expm1(-spec.rate * dt):
                fires = 1
        elif spec.period is not None:
            self._acc[i] += dt
            while self._acc[i] >= spec.period:
                self._acc[i] -= spec.period
                fires += 1
        return fires * spec.burst

    def _serving_decisions(self, tick: int) -> None:
        """Draw this tick's rate/period victims (once per tick)."""
        if self._decided_tick == tick:
            return
        self._decided_tick = tick
        self._decisions = {}
        self._prev_known = self._known or self._prev_known
        self._known = set()
        now = float(self._clock())
        pool = sorted(self._prev_known)
        for i, spec in enumerate(self.plan.specs):
            if (spec.kind not in _SERVING_KINDS
                    or (spec.rate is None and spec.period is None)):
                continue
            fires = self._draw_fires(i, spec, now)
            if not fires:
                continue
            if spec.replica is not None:
                self._decisions.setdefault(spec.replica, spec)
                continue
            victims = (self._chaos_rng.sample(pool, min(fires, len(pool)))
                       if pool else [])
            for v in victims:
                self._decisions.setdefault(v, spec)

    # -- hooks -------------------------------------------------------------

    def on_serving_tick(self, tick: int, replica: int,
                        rate_only: bool = False) -> str | None:
        """Base one-shot specs first (unless ``rate_only`` — subprocess
        workers already run those in-process), then this tick's
        rate/period decision for ``replica``, if any."""
        self._serving_decisions(tick)
        self._known.add(replica)
        if not rate_only:
            kind = super().on_serving_tick(tick, replica)
            if kind is not None:
                self._record(kind, replica, tick)
                return kind
        spec = self._decisions.pop(replica, None)
        if spec is None:
            return None
        self._emit(spec, step=tick, replica=replica)
        self.last_fired = spec
        self._record(spec.kind, replica, tick)
        return spec.kind

    def _draw_wire_fires(self, i: int, spec: FaultSpec, replica: int,
                         now: float) -> int:
        """Per-(spec, replica) twin of ``_draw_fires`` for wire lines.
        The first line on a pipe anchors that pipe's epoch."""
        key = (i, replica)
        last = self._wire_t.get(key)
        self._wire_t[key] = now
        if last is None:
            return 0
        dt = max(0.0, now - last)
        if spec.rate is not None:
            return int(
                self._chaos_rng.random() < -math.expm1(-spec.rate * dt))
        acc = self._wire_acc.get(key, 0.0) + dt
        fires = 0
        while acc >= spec.period:
            acc -= spec.period
            fires += 1
        self._wire_acc[key] = acc
        return fires

    def on_wire(self, replica: int) -> FaultSpec | None:
        """The wire-fault draw for one received line on ``replica``.
        tick= wire specs are one-shot at/after that tick; rate/period
        specs use the same machinery as serving faults; bare p= specs
        draw per line."""
        tick = self._decided_tick or 0
        now = float(self._clock())
        for i, spec in enumerate(self.plan.specs):
            if (spec.kind not in _WIRE_KINDS
                    or (spec.replica is not None
                        and spec.replica != replica)):
                continue
            if spec.tick is not None:
                if (tick >= spec.tick
                        and self._once(f"{i}_{spec.kind}@{spec.tick}"
                                       + (f"_r{spec.replica}"
                                          if spec.replica is not None
                                          else ""))):
                    return spec
                continue
            if spec.rate is not None or spec.period is not None:
                if self._draw_wire_fires(i, spec, replica, now):
                    return spec
                continue
            if self._chaos_rng.random() < spec.p:
                return spec
        return None

    def mangle_recv(self, replica: int,
                    line: str) -> tuple[str | None, str | None]:
        """Apply at most one wire fault to a received line. Returns
        ``(line, kind)``: the (possibly mangled) line to deliver — None
        means the line was dropped — and the fault kind applied (None
        when the wire was clean)."""
        spec = self.on_wire(replica)
        if spec is None:
            return line, None
        tick = self._decided_tick or 0
        self._emit(spec, step=tick, replica=replica)
        self._record(spec.kind, replica, tick)
        if spec.kind == "wire_drop":
            return None, spec.kind
        if spec.kind == "wire_delay":
            time.sleep(spec.ms / 1e3)
            return line, spec.kind
        body = line.rstrip("\n")
        if spec.kind == "wire_torn":
            return body[: max(1, len(body) // 2)] + "\n", spec.kind
        # wire_corrupt: splice garbage mid-line — guaranteed non-JSON
        mid = max(1, len(body) // 2)
        return body[:mid] + '\x00{"~garbage' + body[mid:] + "\n", spec.kind

    def _record(self, kind: str, replica: int, tick: int) -> None:
        self.injected.append(dict(kind=kind, replica=replica, tick=tick,
                                  time=float(self._clock())))


# -- MTTR analysis ---------------------------------------------------------

#: Telemetry events that mean "the router noticed", per fault surface.
_DETECT_EVENTS = frozenset((
    "replica_dead", "quarantine", "wire_fault_detected", "wire_timeout",
    "wire_retry", "wire_slow", "handoff_aborted"))
#: Events that mean "the fleet healed": a quarantined/respawned replica
#: passing its canary back to HEALTHY.
_RECOVER_EVENTS = frozenset(("rejoin",))
#: Fault kinds that need no replica-level recovery — detection IS the
#: recovery (a delayed op completing, a slow step absorbed).
_SELF_HEALING = frozenset(("wire_delay", "replica_slow"))


def _percentile(values: list[float], q: float) -> float:
    xs = sorted(values)
    if not xs:
        return float("nan")
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def recovery_table(events: list[dict]) -> dict[str, dict]:
    """Join injection events with detection + recovery events into a
    per-fault-class table: ``{kind: {injected, detected, recovered,
    mttr_p50_s, mttr_p95_s, mttr_max_s}}``.

    ``events`` are router telemetry event rows ({"event", "time",
    "replica"?, "fault"?, ...}) in time order — the ring
    (``telemetry.recent_events``) for short runs, the
    ``router_metrics_rank*.jsonl`` "event" rows for soaks (the ring is
    bounded). Injections are ``fault_injected`` / ``wire_fault`` rows
    (the router emits one per applied fault, stamped with ``fault=``);
    a detection is the first detect-class event on the same replica at
    or after the injection; recovery is the first ``rejoin`` on that
    replica after detection. MTTR = recovery − injection. Self-healing
    kinds (wire_delay, replica_slow) count detection as recovery."""
    rows = sorted((e for e in events if "event" in e),
                  key=lambda e: float(e.get("time", 0.0)))
    table: dict[str, dict] = {}
    mttrs: dict[str, list[float]] = {}
    for i, e in enumerate(rows):
        if e["event"] not in ("fault_injected", "wire_fault"):
            continue
        kind = str(e.get("fault", "unknown"))
        rep = e.get("replica")
        t0 = float(e.get("time", 0.0))
        ent = table.setdefault(kind, dict(
            injected=0, detected=0, recovered=0))
        ent["injected"] += 1
        det_t = None
        for later in rows[i:]:
            if (later["event"] in _DETECT_EVENTS
                    and later.get("replica") == rep
                    and float(later.get("time", 0.0)) >= t0):
                det_t = float(later.get("time", 0.0))
                break
        if det_t is None:
            continue
        ent["detected"] += 1
        if kind in _SELF_HEALING:
            ent["recovered"] += 1
            mttrs.setdefault(kind, []).append(det_t - t0)
            continue
        for later in rows[i:]:
            if (later["event"] in _RECOVER_EVENTS
                    and later.get("replica") == rep
                    and float(later.get("time", 0.0)) >= det_t):
                ent["recovered"] += 1
                mttrs.setdefault(kind, []).append(
                    float(later.get("time", 0.0)) - t0)
                break
    for kind, ent in table.items():
        xs = mttrs.get(kind, [])
        ent["mttr_p50_s"] = round(_percentile(xs, 0.50), 4) if xs else None
        ent["mttr_p95_s"] = round(_percentile(xs, 0.95), 4) if xs else None
        ent["mttr_max_s"] = round(max(xs), 4) if xs else None
    return table
