"""Deterministic fault injection — the chaos half of SURVEY.md §5.

``run.py`` can detect crashes and hung ranks and resize the group, but
nothing could *prove* recovery worked: every fault-tolerance test had to
hand-roll its own marker-file crash worker. This module is the shared
harness. A ``FaultPlan`` is parsed from the ``PTD_FAULTS`` env spec (or
``run.py --faults``), e.g.::

    crash@step=7,rank=1; hang@step=12,rank=0; nan@step=9; preempt@step=15;
    ckpt_corrupt@step=20; slow_io@p=0.3,ms=200; io_err@n=2

and a ``FaultInjector`` fires it through hooks the Trainer step loop, the
data loader, and the checkpoint save path already call:

  * ``crash@step=S[,rank=R][,code=C]`` — the rank exits ``C`` (default
    41) just before optimizer step S;
  * ``hang@step=S[,rank=R]`` — the rank SIGSTOPs itself (alive, silent,
    never exits — the collective-wedge analog heartbeats must catch);
  * ``preempt@step=S[,rank=R]`` — the rank SIGTERMs itself: the
    Trainer's preemption handler finishes step S, forces a durable
    checkpoint and exits ``EXIT_PREEMPTED``;
  * ``nan@step=S[,rank=R][,layer=L]`` — without ``layer``, step S's loss
    is poisoned to NaN on the host so the anomaly tripwire records it
    and the watchdog raises; with ``layer=L`` the Trainer instead
    poisons layer L's params BEFORE step S, so the non-finite values
    flow through the real compiled model and the in-graph NaN
    provenance (``diag/first_bad_layer``, telemetry/diagnostics.py —
    requires ``diagnostics`` on) must pinpoint exactly that layer in
    the resulting events;
  * ``ckpt_corrupt[@step=S][,rank=R]`` — the first checkpoint committed
    at/after step S has its largest payload file bit-flipped AFTER its
    integrity manifest is written (a torn/corrupted save the verify
    chain must detect and walk past);
  * ``slow_io[@p=P][,ms=M][,rank=R]`` — I/O hooks sleep M ms with
    probability P (tail-latency injection);
  * ``io_err[@p=P][,n=N][,rank=R]`` — I/O hooks raise OSError with
    probability P, at most N times total (N=0 → uncapped): the transient
    class ``faults.retry`` must absorb;
  * ``replica_crash@tick=T[,replica=I]`` / ``replica_hang@tick=T[...]``
    / ``replica_nan@tick=T[...]`` — SERVING faults (ISSUE 9), fired by
    the replica router's scheduler loop at router tick T against
    replica I (any replica when omitted): crash kills the replica
    mid-stream, hang freezes it without exiting (the progress-watermark
    watchdog must catch it), nan poisons its params so the
    engine-health tripwire declares it sick and the router quarantines
    it. The router redispatches the victim's in-flight requests to
    survivors — `serving/router.py` owns the application, this module
    owns the schedule. Serving faults also accept ``rate=R`` (Poisson
    events/sec over wall-clock), ``period=P`` (every P seconds) and
    ``burst=B`` instead of a one-shot ``tick=`` — those specs are inert
    under the base injector and fire through ``faults.chaos
    .ChaosSchedule``; ``replica_slow`` stretches a replica's next step
    by ``ms=`` without tripping the watchdog;
  * ``wire_corrupt`` / ``wire_torn`` / ``wire_delay`` / ``wire_drop``
    (``@tick=T|rate=R|period=P|p=P[,replica=I][,ms=M]``) — wire-level
    faults (ISSUE 19) applied by a ChaosSchedule at the subprocess
    line-JSON boundary: corrupt mangles a response into invalid JSON,
    torn truncates it, delay sleeps ``ms=``, drop loses the line (the
    op must surface via its timeout). The router classifies them as
    protocol faults → quarantine, never an uncaught raise.

Every injection emits a TelemetryEvent before it acts, so the launcher's
per-incarnation summaries show *why* an incarnation died. Step-targeted
faults are one-shot: fired markers persist in ``PTD_FAULTS_STATE`` (the
launcher provisions a directory that survives restarts), so a crash at
step 7 does not re-fire after the relaunched incarnation resumes and
trains step 7's successor — without the marker every deterministic crash
would be an infinite crash loop.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import sys
import time

from pytorchdistributed_tpu.telemetry.events import (
    EVENT_FAULT,
    EventLog,
)

FAULTS_ENV = "PTD_FAULTS"
FAULTS_STATE_ENV = "PTD_FAULTS_STATE"

#: Worker exit code for a graceful preemption (SIGTERM → finish step →
#: forced durable checkpoint → exit). Distinct from every failure code in
#: the repo so the launcher can restart it WITHOUT charging the
#: same-rank failure tracker that drives elastic shrink.
EXIT_PREEMPTED = 77

#: Default exit code for an injected crash (arbitrary, recognizable).
CRASH_EXIT_CODE = 41

_STEP_KINDS = ("crash", "hang", "preempt", "nan")
_IO_KINDS = ("slow_io", "io_err")
#: Serving-phase faults (ISSUE 9): fired by the replica ROUTER's tick
#: loop (serving/router.py), targeted at a replica index instead of a
#: rank — `replica_crash@tick=5,replica=0; replica_hang@tick=9` etc.
#: crash kills the replica mid-stream (in-process: the engine raises and
#: is torn down; subprocess: os._exit), hang freezes it silently (the
#: progress-watermark analog of the SIGSTOP training hang), nan poisons
#: its PARAMS so the engine-health tripwire (params_finite) must declare
#: it sick and the router quarantine it.
_SERVING_KINDS = ("replica_crash", "replica_hang", "replica_nan",
                  "replica_slow")
#: Wire-level faults (ISSUE 19): applied at the router↔worker line-JSON
#: boundary (and the KV-handoff/session payload path) by a ChaosSchedule
#: (faults/chaos.py) — `wire_corrupt` mangles a response line into
#: invalid JSON, `wire_torn` truncates it mid-object, `wire_delay`
#: sleeps ms= before delivery, `wire_drop` loses the line entirely (the
#: op surfaces only via its timeout — indistinguishable from a hang
#: until the retry/watchdog machinery classifies it).
_WIRE_KINDS = ("wire_corrupt", "wire_torn", "wire_delay", "wire_drop")
KINDS = frozenset(_STEP_KINDS + _IO_KINDS + _SERVING_KINDS + _WIRE_KINDS
                  + ("ckpt_corrupt",))


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed ``kind@k=v,...`` entry."""

    kind: str
    step: int | None = None
    rank: int | None = None
    p: float = 1.0
    ms: float = 100.0
    n: int = 0
    code: int = CRASH_EXIT_CODE
    layer: int | None = None    # nan only: poison THIS layer's params
    tick: int | None = None     # serving faults: fire at router tick T
    replica: int | None = None  # serving faults: target replica index
    rate: float | None = None   # chaos: Poisson events/sec (wall-clock)
    period: float | None = None  # chaos: fire every P seconds
    burst: int = 1              # chaos: victims per firing

    def describe(self) -> str:
        parts = [self.kind]
        if self.step is not None:
            parts.append(f"step={self.step}")
        if self.tick is not None:
            parts.append(f"tick={self.tick}")
        if self.rank is not None:
            parts.append(f"rank={self.rank}")
        if self.replica is not None:
            parts.append(f"replica={self.replica}")
        if self.layer is not None:
            parts.append(f"layer={self.layer}")
        if self.rate is not None:
            parts.append(f"rate={self.rate}")
        if self.period is not None:
            parts.append(f"period={self.period}")
        if self.burst != 1:
            parts.append(f"burst={self.burst}")
        return parts[0] + ("@" + ",".join(parts[1:]) if parts[1:] else "")


class FaultPlan:
    """The parsed spec: an ordered list of FaultSpecs."""

    def __init__(self, specs: list[FaultSpec]):
        self.specs = list(specs)

    def __len__(self) -> int:
        return len(self.specs)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        specs = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            kind, _, params = entry.partition("@")
            kind = kind.strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} in {entry!r} "
                    f"(known: {', '.join(sorted(KINDS))})")
            kw: dict = {}
            for item in params.split(",") if params else []:
                item = item.strip()
                if not item:
                    continue
                key, _, val = item.partition("=")
                key, val = key.strip(), val.strip()
                try:
                    if key in ("step", "rank", "n", "code", "layer",
                               "tick", "replica", "burst"):
                        kw[key] = int(val)
                    elif key in ("p", "ms", "rate", "period"):
                        kw[key] = float(val)
                    else:
                        raise ValueError(f"unknown param {key!r}")
                except ValueError as e:
                    raise ValueError(
                        f"bad fault param {item!r} in {entry!r}: {e}"
                    ) from None
            chaos = kind in _SERVING_KINDS or kind in _WIRE_KINDS
            if "layer" in kw and kind != "nan":
                raise ValueError(
                    f"layer= only applies to nan faults (got {entry!r})")
            if kind in _STEP_KINDS and "step" not in kw:
                raise ValueError(
                    f"fault {kind!r} needs step= (got {entry!r})")
            if (kind in _SERVING_KINDS and "tick" not in kw
                    and "rate" not in kw and "period" not in kw):
                raise ValueError(
                    f"fault {kind!r} needs tick=, rate= or period= "
                    f"(got {entry!r})")
            if (kind in _WIRE_KINDS and not any(
                    k in kw for k in ("tick", "rate", "period", "p"))):
                raise ValueError(
                    f"fault {kind!r} needs tick=, rate=, period= or p= "
                    f"(got {entry!r})")
            if ("tick" in kw or "replica" in kw) and not chaos:
                raise ValueError(
                    f"tick=/replica= only apply to serving/wire faults "
                    f"({', '.join(_SERVING_KINDS + _WIRE_KINDS)}; "
                    f"got {entry!r})")
            if (("rate" in kw or "period" in kw or "burst" in kw)
                    and not chaos):
                raise ValueError(
                    f"rate=/period=/burst= only apply to serving/wire "
                    f"faults (got {entry!r})")
            if "rate" in kw and kw["rate"] < 0:
                raise ValueError(f"rate must be >= 0, got {kw['rate']}")
            if "period" in kw and kw["period"] <= 0:
                raise ValueError(
                    f"period must be > 0, got {kw['period']}")
            if "burst" in kw and kw["burst"] < 1:
                raise ValueError(f"burst must be >= 1, got {kw['burst']}")
            if "p" in kw and not 0.0 <= kw["p"] <= 1.0:
                raise ValueError(f"p must be in [0, 1], got {kw['p']}")
            specs.append(FaultSpec(kind=kind, **kw))
        return cls(specs)

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        spec = os.environ.get(FAULTS_ENV, "").strip()
        return cls.parse(spec) if spec else None


class FaultInjector:
    """Fires a FaultPlan through the subsystem hooks for ONE rank.

    One-shot bookkeeping: step-targeted specs record a marker — a file
    in ``state_dir`` when set (survives relaunches; the launcher's
    ``PTD_FAULTS_STATE`` contract), else an in-process set. Probabilistic
    specs draw from a Random seeded on (spec string order, rank), so a
    given plan replays identically."""

    #: The spec behind the most recent ``on_serving_tick`` firing, so a
    #: caller holding only the returned kind string can still read its
    #: parameters (``replica_slow`` needs ``ms=``).
    last_fired: FaultSpec | None = None

    def __init__(self, plan: FaultPlan, *, rank: int = 0,
                 state_dir: str | None = None, events: EventLog | None = None,
                 seed: int = 0):
        self.plan = plan
        self.rank = rank
        self.state_dir = state_dir
        self.events = events
        self._rng = random.Random((seed, rank, len(plan.specs)).__hash__())
        self._fired: set[str] = set()
        self._io_err_count = [0] * len(plan.specs)
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)

    @classmethod
    def from_env(cls) -> "FaultInjector | None":
        plan = FaultPlan.from_env()
        if plan is None:
            return None
        rank = int(os.environ.get("RANK", "0"))
        return cls(plan, rank=rank,
                   state_dir=os.environ.get(FAULTS_STATE_ENV) or None,
                   events=EventLog.from_env(rank))

    # -- one-shot bookkeeping ---------------------------------------------

    def _once(self, key: str) -> bool:
        """True exactly once per (key, rank) across incarnations."""
        key = f"{key}_rank{self.rank}"
        if key in self._fired:
            return False
        self._fired.add(key)
        if self.state_dir:
            marker = os.path.join(self.state_dir, key)
            if os.path.exists(marker):
                return False
            try:
                with open(marker, "x"):
                    pass
            except FileExistsError:
                return False
        return True

    def _emit(self, spec: FaultSpec, **data) -> None:
        if self.events is not None:
            self.events.emit(EVENT_FAULT, step=data.pop("step", -1),
                             fault=spec.kind, spec=spec.describe(), **data)
            self.events.flush()

    def _mine(self, spec: FaultSpec) -> bool:
        return spec.rank is None or spec.rank == self.rank

    # -- hooks -------------------------------------------------------------

    def on_step(self, step: int) -> None:
        """Trainer hook, called just BEFORE optimizer step ``step``
        (1-based, global across incarnations) runs. crash/hang exit here;
        preempt SIGTERMs self so the Trainer's handler finishes the step
        and checkpoints before exiting."""
        for i, spec in enumerate(self.plan.specs):
            if (spec.kind not in ("crash", "hang", "preempt")
                    or not self._mine(spec) or spec.step != step):
                continue
            if not self._once(f"{i}_{spec.kind}@{spec.step}"):
                continue
            self._emit(spec, step=step)
            if spec.kind == "crash":
                sys.stderr.write(
                    f"[faults] rank {self.rank} injected crash at step "
                    f"{step} (exit {spec.code})\n")
                sys.stderr.flush()
                os._exit(spec.code)
            elif spec.kind == "hang":
                sys.stderr.write(
                    f"[faults] rank {self.rank} injected hang at step "
                    f"{step} (SIGSTOP)\n")
                sys.stderr.flush()
                os.kill(os.getpid(), signal.SIGSTOP)
            else:  # preempt: the SIGTERM handler takes it from here
                sys.stderr.write(
                    f"[faults] rank {self.rank} injected preemption at "
                    f"step {step} (SIGTERM)\n")
                sys.stderr.flush()
                os.kill(os.getpid(), signal.SIGTERM)

    def poison_nan(self, step: int) -> bool:
        """Trainer hook, called AFTER step ``step``: True when this
        step's loss should be replaced with NaN (the tripwire/watchdog
        pair must record then raise on it). Layer-targeted nan specs
        take the ``poison_nan_layer`` path instead — never both."""
        for i, spec in enumerate(self.plan.specs):
            if (spec.kind == "nan" and spec.layer is None
                    and self._mine(spec) and spec.step == step
                    and self._once(f"{i}_nan@{spec.step}")):
                self._emit(spec, step=step)
                return True
        return False

    def poison_nan_layer(self, step: int) -> int | None:
        """Trainer hook, called BEFORE step ``step`` runs: the layer
        index whose params should be NaN-poisoned this step (the
        in-graph provenance injection — ISSUE 6), or None. One-shot like
        every step-targeted fault."""
        for i, spec in enumerate(self.plan.specs):
            if (spec.kind == "nan" and spec.layer is not None
                    and self._mine(spec) and spec.step == step
                    and self._once(f"{i}_nan@{spec.step}")):
                self._emit(spec, step=step, layer=spec.layer)
                sys.stderr.write(
                    f"[faults] rank {self.rank} injected layer-{spec.layer} "
                    f"NaN at step {step}\n")
                sys.stderr.flush()
                return spec.layer
        return None

    def on_serving_tick(self, tick: int, replica: int) -> str | None:
        """Serving-phase hook (ISSUE 9), called by the replica router
        (or a subprocess replica worker) once per scheduler tick per
        replica BEFORE that replica steps. Returns the fault kind to
        apply to this replica at this tick — ``"replica_crash"`` /
        ``"replica_hang"`` / ``"replica_nan"`` — or None. The CALLER
        applies it (an in-process replica cannot os._exit the router);
        one-shot markers keep a tick-targeted fault from re-firing, and
        every firing emits a TelemetryEvent first, so the run dir says
        why a replica died."""
        for i, spec in enumerate(self.plan.specs):
            if (spec.kind not in _SERVING_KINDS or spec.tick != tick
                    or (spec.replica is not None
                        and spec.replica != replica)):
                continue
            # replica= omitted means ANY replica — ONE victim (the
            # first consult at tick T), so the marker must not be
            # per-replica or an untargeted crash would kill the fleet
            marker = (f"{i}_{spec.kind}@{spec.tick}"
                      + (f"_r{replica}" if spec.replica is not None
                         else ""))
            if not self._once(marker):
                continue
            self._emit(spec, step=tick, replica=replica)
            sys.stderr.write(
                f"[faults] injected {spec.kind} on replica {replica} at "
                f"serving tick {tick}\n")
            sys.stderr.flush()
            self.last_fired = spec
            return spec.kind
        return None

    def on_io(self, what: str, *, step: int = -1) -> None:
        """I/O-path hook (data file reads, loader batches, checkpoint
        save/restore): slow_io sleeps, io_err raises OSError — which the
        retry-wrapped call sites absorb up to their policy bound."""
        for i, spec in enumerate(self.plan.specs):
            if not self._mine(spec):
                continue
            if spec.kind == "slow_io":
                if self._rng.random() < spec.p:
                    self._emit(spec, step=step, what=what, ms=spec.ms)
                    time.sleep(spec.ms / 1e3)
            elif spec.kind == "io_err":
                if spec.n and self._io_err_count[i] >= spec.n:
                    continue
                if self._rng.random() < spec.p:
                    self._io_err_count[i] += 1
                    self._emit(spec, step=step, what=what,
                               count=self._io_err_count[i])
                    raise OSError(
                        f"injected io_err ({what}, "
                        f"failure {self._io_err_count[i]})")

    def on_checkpoint_saved(self, step: int, step_dir) -> bool:
        """Checkpoint hook, called after a save COMMITS and its manifest
        is written: a matching ckpt_corrupt spec bit-flips the largest
        payload file under ``step_dir`` (manifest untouched — verification
        must catch the mismatch). Returns whether corruption happened."""
        import pathlib

        step_dir = pathlib.Path(step_dir)
        for i, spec in enumerate(self.plan.specs):
            if spec.kind != "ckpt_corrupt" or not self._mine(spec):
                continue
            if spec.step is not None and step < spec.step:
                continue
            if not self._once(f"{i}_ckpt_corrupt"):
                continue
            files = sorted(
                (p for p in step_dir.rglob("*")
                 if p.is_file() and "manifest" not in p.name.lower()),
                key=lambda p: p.stat().st_size, reverse=True)
            if not files:
                return False
            target = files[0]
            data = bytearray(target.read_bytes())
            span = min(64, len(data))
            for j in range(span):
                data[j] ^= 0xFF
            target.write_bytes(bytes(data))
            self._emit(spec, step=step,
                       file=str(target.relative_to(step_dir)))
            sys.stderr.write(
                f"[faults] rank {self.rank} corrupted checkpoint step "
                f"{step} ({target.name})\n")
            sys.stderr.flush()
            return True
        return False


# Process-global injector: every subsystem (Trainer, CheckpointManager,
# data loaders) shares ONE instance so count-limited specs (io_err@n=2)
# mean "2 failures in this process", not 2 per component. Cached on first
# use; tests that mutate PTD_FAULTS call reset_active().
_ACTIVE: list = [False, None]  # [resolved?, injector]


def active() -> FaultInjector | None:
    if not _ACTIVE[0]:
        _ACTIVE[0], _ACTIVE[1] = True, FaultInjector.from_env()
    return _ACTIVE[1]


def reset_active() -> None:
    _ACTIVE[0], _ACTIVE[1] = False, None
