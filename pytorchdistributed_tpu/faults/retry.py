"""Bounded retry with exponential backoff + jitter — the I/O hardening
half of the fault subsystem (SURVEY.md §5).

Checkpoint save/restore and data file reads go through ``retry``: a
transient filesystem error (GCS 5xx surfacing as OSError, an NFS hiccup,
a page-cache eviction race) costs a delay and a durable TelemetryEvent
instead of the incarnation — restarting a pod-scale job to re-read one
file is the most expensive retry policy there is. Permanent errors
(anything outside ``policy.retry_on``, or ``max_attempts`` exhausted)
still raise: retry must narrow the failure domain, never hide it.

Jitter is multiplicative and seeded per call site (``rng``): a thundering
herd of ranks retrying the same shared-filesystem path must decorrelate,
but the chaos suite needs reproducible schedules — both callers pick.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, TypeVar

from pytorchdistributed_tpu.telemetry.events import EVENT_RETRY

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``max_attempts`` total tries; delay before try k+1 is
    ``min(base_delay_s * backoff**(k-1), max_delay_s)`` scaled by a
    uniform jitter in ``[1, 1 + jitter]``. Only ``retry_on`` exception
    types are retried — everything else propagates on the first throw."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    backoff: float = 2.0
    max_delay_s: float = 2.0
    jitter: float = 0.25
    retry_on: tuple[type[BaseException], ...] = (OSError,)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        base = min(self.base_delay_s * self.backoff ** (attempt - 1),
                   self.max_delay_s)
        return base * (1.0 + self.jitter * rng.random())


#: Default policy for checkpoint/data I/O: 4 tries over ~0.35 s worst
#: case — long enough to ride out a filesystem hiccup, short enough that
#: a genuinely dead disk fails the rank before the heartbeat timeout
#: attributes the stall to a hang.
IO_RETRY = RetryPolicy()


def retry(fn: Callable[[], T], *, policy: RetryPolicy = IO_RETRY,
          describe: str = "", events=None, rng: random.Random | None = None,
          sleep: Callable[[float], None] = time.sleep) -> T:
    """Call ``fn`` until it returns, retrying ``policy.retry_on`` failures
    with backoff. Each retry emits an ``EVENT_RETRY`` TelemetryEvent on
    ``events`` (an EventLog, or None) so post-mortems can see the I/O
    flakiness that preceded a failure; the final attempt's exception
    propagates unchanged."""
    rng = rng if rng is not None else random.Random()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except policy.retry_on as e:
            if attempt >= policy.max_attempts:
                raise
            delay = policy.delay(attempt, rng)
            if events is not None:
                events.emit(EVENT_RETRY, step=-1, op=describe or "io",
                            attempt=attempt, max_attempts=policy.max_attempts,
                            delay_ms=round(delay * 1e3, 3),
                            error=f"{type(e).__name__}: {e}"[:200])
            sleep(delay)


def retryable(policy: RetryPolicy = IO_RETRY, *, describe: str = "",
              events=None):
    """Decorator form of ``retry`` for fixed call sites."""

    def wrap(fn):
        def inner(*args, **kwargs):
            return retry(lambda: fn(*args, **kwargs), policy=policy,
                         describe=describe or fn.__name__, events=events)

        inner.__name__ = getattr(fn, "__name__", "retryable")
        return inner

    return wrap
