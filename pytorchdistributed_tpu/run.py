"""torchrun-style launcher CLI with elastic restart.

    python -m pytorchdistributed_tpu.run --nproc-per-node 2 train.py --lr 3e-4

The agent process (this module) spawns one worker per rank with the env
contract the reference's scripts read (RANK / WORLD_SIZE / LOCAL_RANK /
MASTER_ADDR / MASTER_PORT — reference ddp_gpus_torchrun.py:14-19), watches
for failures, and on ``--max-restarts > 0`` tears the group down and
relaunches it — restart-from-checkpoint semantics (workers are expected to
resume via Trainer.fit(resume=True); SURVEY.md §5 "Failure detection /
elastic recovery").

``--heartbeat-timeout T`` adds *hung*-rank detection on top of exit
watching: a rank wedged in a collective (the NCCL-deadlock analog) never
exits, so the agent also tracks per-rank liveness files
(runtime/heartbeat.py; the Trainer beats at its device-sync points) and
treats a rank silent for more than T seconds as failed — kill the group,
relaunch if restarts remain.

``--elastic-min-nproc M`` enables torchrun's ``--nnodes=min:max`` resize
semantics (beyond the reference, which pins ``--nproc_per_node=2``,
ddp_gpus_torchrun.py:102): when the SAME single rank fails twice
consecutively, the group relaunches one worker smaller (never below M)
and ranks renumber — capacity reduction so training continues, NOT
slot exclusion (this launcher assigns no fixed hardware to a rank; a
failure tied to the rank NUMBER itself would move with the renumbering).
Shrinks are bounded by ``nproc − M`` and are not charged against
``--max-restarts``; group-wide failures (more than one nonzero exit, e.g.
a bad script arg) reset the per-rank tracker and only consume restarts.
Observing a repeat takes one same-size relaunch, so the flag needs
``--max-restarts ≥ 1`` to ever fire. Workers read the new WORLD_SIZE from
the env contract and re-shard their data accordingly; note the Trainer's
mid-epoch resume geometry guard refuses to fast-forward across a
world-size change (resume restarts the epoch boundary from the
checkpoint instead).

A shrunken group does not stay shrunken for the life of the job
(torchrun's max bound is standing, not a ratchet): a charged relaunch
boundary after a shrink probes one worker BIGGER again, back toward the
original ``--nproc-per-node`` — but only when the incarnation that just
failed had first run HEALTHY for ``--elastic-regrow-after`` seconds.
The uptime gate is what separates "stable group hit an independent
transient, worth probing for returned capacity" from "still failing
fast, the shrink evidence is not done accumulating": without it a
probe on every restart would reset the consecutive-failure tracker
before it ever reached two, making sizes below max−1 unreachable for a
persistently bad slot. Probes ride restarts the group was paying for
anyway, so flapping is bounded by the ``--max-restarts`` budget. There
is no external "node joined" signal on a single-host agent (torchrun
regrows on rendezvous arrivals), so a stable-then-interrupted relaunch
boundary is the honest stand-in.

Preemption + chaos (SURVEY.md §5 completion): a SIGTERM/SIGINT received
by the agent is FORWARDED to the workers, whose Trainers drain a durable
checkpoint and exit ``EXIT_PREEMPTED`` within ``--preempt-grace`` seconds
— Ctrl-C never orphans a group. A worker exiting ``EXIT_PREEMPTED`` on
its own (the platform preempted one VM, or an injected ``preempt@step``)
is restarted but never charged to the same-rank tracker above: reclaimed
capacity is not evidence of a bad slot. ``--faults`` exports a
deterministic fault-injection spec (``PTD_FAULTS``; see faults/inject.py)
plus a marker directory (``PTD_FAULTS_STATE``) that keeps step-targeted
faults one-shot across relaunches — the chaos-suite rig every
fault-tolerance claim in this repo is tested through.
"""

from __future__ import annotations

import argparse
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

from pytorchdistributed_tpu.faults.inject import (
    EXIT_PREEMPTED,
    FAULTS_ENV,
    FAULTS_STATE_ENV,
    FaultPlan,
)
from pytorchdistributed_tpu.runtime.heartbeat import (
    HEARTBEAT_DIR_ENV,
    stale_ranks,
)
from pytorchdistributed_tpu.telemetry.events import (
    TELEMETRY_DIR_ENV,
    summarize_new_events,
)


def free_port() -> int:
    """An OS-assigned free localhost port (the MASTER_PORT of the env
    contract). Public: the serving replica router's subprocess mode
    reuses the same rendezvous contract for its workers."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_group(argv, nproc: int, port: int,
                 devices_per_proc: int | None,
                 heartbeat_dir: str | None = None,
                 telemetry_dir: str | None = None,
                 extra_env: dict[str, str] | None = None,
                 ) -> list[subprocess.Popen]:
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        env.update({
            "RANK": str(rank),
            "LOCAL_RANK": str(rank),
            "WORLD_SIZE": str(nproc),
            "MASTER_ADDR": "localhost",
            "MASTER_PORT": str(port),
        })
        if heartbeat_dir is not None:
            env[HEARTBEAT_DIR_ENV] = heartbeat_dir
        if telemetry_dir is not None:
            env[TELEMETRY_DIR_ENV] = telemetry_dir
        if devices_per_proc is not None:
            from pytorchdistributed_tpu.runtime.launch import sim_device_flags
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = sim_device_flags(
                env.get("XLA_FLAGS", ""), devices_per_proc)
        procs.append(subprocess.Popen([sys.executable] + argv, env=env))
    return procs


def kill_group(procs, *, sig: int = signal.SIGTERM,
               grace: float = 10.0) -> None:
    """Signal every live worker and SIGKILL stragglers after ``grace``
    seconds. The default (SIGTERM, 10 s) is the failure-teardown path; the
    agent's signal forwarding reuses it with the received signal and
    ``--preempt-grace`` so Trainers get one window to drain durable
    checkpoints — one escalation point, not two. Public: the serving
    replica router's subprocess teardown uses the same escalation so a
    drained router can never leave an orphan replica worker."""
    for p in procs:
        if p.poll() is None:
            p.send_signal(sig)
            # a SIGSTOPped (hung-and-frozen) worker can't handle SIGTERM;
            # wake it so termination isn't stuck behind the escalation
            p.send_signal(signal.SIGCONT)
    deadline = time.time() + max(grace, 0.1)
    for p in procs:
        try:
            p.wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()


def _forward_signal_and_drain(procs, signum: int, grace: float) -> None:
    """Agent received SIGTERM/SIGINT: forward it to every live worker —
    Ctrl-C must not orphan the group, and a platform preemption notice
    must reach the Trainers (SIGINT is translated to SIGTERM, the signal
    their preemption handler owns)."""
    fwd = signal.SIGTERM if signum == signal.SIGINT else signum
    kill_group(procs, sig=fwd, grace=grace)


def main(argv=None) -> int:
    owned_dirs: list[str] = []
    try:
        return _main(argv, owned_dirs)
    finally:
        for d in owned_dirs:
            shutil.rmtree(d, ignore_errors=True)


def _main(argv, owned_dirs: list[str]) -> int:
    parser = argparse.ArgumentParser(
        "pytorchdistributed_tpu.run",
        description="torchrun-equivalent launcher "
                    "(reference ddp_gpus_torchrun.py:102)")
    parser.add_argument("--nproc-per-node", type=int, default=1)
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="relaunch the whole group this many times if a "
                             "rank fails (workers resume from checkpoints)")
    parser.add_argument("--monitor-interval", type=float, default=0.2)
    parser.add_argument("--heartbeat-timeout", type=float, default=0.0,
                        help="seconds of per-rank heartbeat silence before "
                             "the group counts as hung and is relaunched "
                             "(0 = exit-watching only)")
    parser.add_argument("--heartbeat-grace", type=float, default=300.0,
                        help="extra allowance before a rank's FIRST beat "
                             "(imports + first XLA compile)")
    parser.add_argument("--devices-per-proc", type=int, default=None,
                        help="CPU-sim chips per process (sets JAX_PLATFORMS="
                             "cpu + xla_force_host_platform_device_count)")
    parser.add_argument("--telemetry-dir", type=str, default=None,
                        help="run directory for the unified telemetry "
                             "subsystem: exported to workers as "
                             f"{TELEMETRY_DIR_ENV} (Trainers write spans/"
                             "metrics/events per rank there) and the agent "
                             "prints each incarnation's tripwire events "
                             "next to its restart decisions; read back "
                             "with `python -m pytorchdistributed_tpu."
                             "telemetry report <dir>`")
    parser.add_argument("--elastic-min-nproc", type=int, default=0,
                        help="allow the group to relaunch SMALLER (down to "
                             "this size) when the same rank fails twice in "
                             "a row, and to probe back BIGGER (up to "
                             "--nproc-per-node) on later restarts — "
                             "torchrun --nnodes=min:max resize semantics "
                             "(0 = fixed size)")
    parser.add_argument("--preempt-grace", type=float, default=30.0,
                        help="seconds workers get to drain a graceful "
                             "checkpoint after the agent forwards a "
                             "SIGTERM/SIGINT it received, before the "
                             "escalating teardown")
    parser.add_argument("--faults", type=str, default=None,
                        help="deterministic fault-injection spec exported "
                             f"to workers as {FAULTS_ENV} (e.g. "
                             "'crash@step=7,rank=1; nan@step=9; "
                             "preempt@step=15'); one-shot markers persist "
                             f"across relaunches via {FAULTS_STATE_ENV}")
    parser.add_argument("--elastic-regrow-after", type=float, default=30.0,
                        help="minimum healthy uptime (s) of the failing "
                             "incarnation before a restart also probes the "
                             "group one worker bigger; failures earlier "
                             "than this are treated as continuing "
                             "instability and never regrow")
    parser.add_argument("script", help="training script to run")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    worker_argv = [args.script] + args.script_args
    restarts = 0
    nproc = args.nproc_per_node
    last_failed, consecutive = None, 0
    if args.telemetry_dir is not None:
        os.makedirs(args.telemetry_dir, exist_ok=True)
    # Fault-injection contract: --faults (or an inherited PTD_FAULTS)
    # reaches workers through their spawn environment — never by
    # mutating the agent's own os.environ, which would leak specs into
    # later in-process main() calls and unrelated subprocesses. The
    # agent provisions ONE marker directory for the whole run so
    # step-targeted faults stay one-shot across relaunches (a crash@step
    # spec that re-fired every incarnation would be an infinite crash
    # loop, not a test).
    faults_env: dict[str, str] = {}
    if args.faults:
        FaultPlan.parse(args.faults)  # fail fast on a typo'd spec
        faults_env[FAULTS_ENV] = args.faults
    if ((args.faults or os.environ.get(FAULTS_ENV))
            and not os.environ.get(FAULTS_STATE_ENV)):
        state_dir = tempfile.mkdtemp(prefix="ptd_faults_")
        faults_env[FAULTS_STATE_ENV] = state_dir
        owned_dirs.append(state_dir)
    # Signal forwarding (graceful teardown / preemption notice): the
    # handler only records the signal — forwarding and the grace wait
    # happen in the monitor loop, outside async-signal context.
    signals_seen: list[int] = []
    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, lambda s, f: signals_seen.append(s))
    # Per-incarnation telemetry aggregation: byte offsets into the
    # per-rank event files advance as the agent reports, so each summary
    # covers exactly the incarnation that just ended — the tripwire
    # analog of the heartbeat state printed on the same stream.
    tele_offsets: dict[str, int] = {}

    def report_telemetry() -> None:
        if args.telemetry_dir is None:
            return
        summary = summarize_new_events(args.telemetry_dir, tele_offsets)
        if summary is not None:
            print(f"[run] telemetry: {summary}", file=sys.stderr)
    if args.elastic_min_nproc > 0 and args.max_restarts < 1:
        print("[run] warning: --elastic-min-nproc needs --max-restarts >= 1 "
              "to observe a repeated failure; it will never fire",
              file=sys.stderr)
    while True:
        port = free_port()
        # fresh heartbeat dir per incarnation: a relaunch must not inherit
        # the dead group's file mtimes
        hb_dir = (tempfile.mkdtemp(prefix="ptd_heartbeat_")
                  if args.heartbeat_timeout > 0 else None)
        spawned_at = time.time()
        procs = _spawn_group(worker_argv, nproc, port,
                             args.devices_per_proc, hb_dir,
                             args.telemetry_dir, faults_env)
        failed, why = [], "failed"
        while not failed:
            time.sleep(args.monitor_interval)
            if signals_seen:
                # graceful teardown: forward the signal so Trainers drain
                # durable checkpoints (never orphan workers on Ctrl-C)
                signum = signals_seen[0]
                print(f"[run] received {signal.Signals(signum).name}; "
                      f"forwarding to workers "
                      f"(grace {args.preempt_grace}s)", file=sys.stderr)
                _forward_signal_and_drain(procs, signum, args.preempt_grace)
                if hb_dir is not None:
                    shutil.rmtree(hb_dir, ignore_errors=True)
                report_telemetry()
                codes = [p.poll() for p in procs]
                if all(c == 0 for c in codes):
                    return 0
                if all(c in (0, EXIT_PREEMPTED) for c in codes):
                    print("[run] workers preempted gracefully "
                          "(checkpoints drained)", file=sys.stderr)
                    return EXIT_PREEMPTED
                return 128 + signum
            codes = [p.poll() for p in procs]
            suspect, why = [], "failed"
            if any(c not in (None, 0) for c in codes):
                suspect = [r for r, c in enumerate(codes)
                           if c not in (None, 0)]
            elif all(c == 0 for c in codes):
                if hb_dir is not None:
                    shutil.rmtree(hb_dir, ignore_errors=True)
                report_telemetry()
                return 0
            elif hb_dir is not None:
                hung = stale_ranks(hb_dir, nproc,
                                   timeout=args.heartbeat_timeout,
                                   grace=args.heartbeat_grace,
                                   now=time.time(), baseline=spawned_at)
                # only live ranks count as hung — a cleanly-exited rank
                # stops beating legitimately while the rest finish up
                hung = [r for r in hung if codes[r] is None]
                if hung:
                    suspect, why = hung, "hung (heartbeat stale)"
            if not suspect:
                continue
            # settle window before attributing single-vs-group: in a
            # group-wide crash (or group-wide collective wedge) the
            # siblings fail within moments of the first-seen member, and
            # sampling too early would misread it as one bad rank.
            # Floored at 0.5 s — monitor-interval alone can be shorter
            # than sibling skew.
            time.sleep(max(args.monitor_interval, 0.5))
            codes = [p.poll() for p in procs]
            if all(c == 0 for c in codes):
                # the suspects were finishing up (e.g. a slow final
                # checkpoint save outlived the heartbeat timeout) and the
                # whole group completed during the settle — success
                if hb_dir is not None:
                    shutil.rmtree(hb_dir, ignore_errors=True)
                report_telemetry()
                return 0
            exited = [r for r, c in enumerate(codes)
                      if c not in (None, 0)]
            if why == "failed":
                failed = exited  # nonzero codes are stable: non-empty
            else:
                # hung: the cohort is the still-live stale ranks PLUS any
                # sibling that crashed during the settle. Empty cohort =
                # false alarm (the stale rank exited 0 while siblings
                # keep working) — resume monitoring, nothing failed.
                stale = stale_ranks(hb_dir, nproc,
                                    timeout=args.heartbeat_timeout,
                                    grace=args.heartbeat_grace,
                                    now=time.time(), baseline=spawned_at)
                failed = sorted(set(r for r in stale if codes[r] is None)
                                | set(exited))
        # Snapshot BEFORE the teardown: kill_group can block ~10s on a
        # SIGTERM-ignoring worker, and that wait is not health either.
        detected_at = time.time()
        kill_group(procs)
        # aggregate this incarnation's tripwire events next to the
        # failure attribution below (NaN storms and loss spikes are the
        # why behind many a nonzero exit)
        report_telemetry()
        # Healthy uptime of the incarnation that just failed (feeds the
        # regrow gate below). Clean exits: wall clock to detection —
        # lag is ~monitor-interval + the settle window. HUNG cohorts:
        # detection latency (heartbeat grace/timeout, minutes by default)
        # is NOT health — credit the cohort only up to its last observed
        # beat, 0 if it never beat; otherwise a slot that persistently
        # WEDGES would pass the gate on pure detection lag and
        # regrow-flapping would defeat the shrink tracker (the exact
        # pathology the gate exists to prevent).
        if why == "failed":
            healthy_for = detected_at - spawned_at
        else:
            beats = []
            for r in failed:
                try:
                    beats.append(os.path.getmtime(
                        os.path.join(hb_dir, f"rank{r}")))
                except OSError:
                    pass
            healthy_for = max(0.0, max(beats, default=spawned_at)
                              - spawned_at)
        if hb_dir is not None:  # each incarnation gets a fresh dir
            shutil.rmtree(hb_dir, ignore_errors=True)
        failed_rank = failed[0]
        # Graceful preemption (EXIT_PREEMPTED): restart-worthy — the
        # checkpoint is durable and training should continue — but NEVER
        # attributed to the rank. A platform reclaiming capacity says
        # nothing about the slot's health, so the same-rank tracker that
        # drives elastic shrink is left untouched (acceptance: preemption
        # exits are never counted by the shrink tracker).
        preempted = (why == "failed"
                     and all(codes[r] == EXIT_PREEMPTED for r in failed))
        if preempted:
            why = "preempted (graceful, checkpoint drained)"
        elif len(failed) > 1:
            # group-wide failure (bad args, rendezvous breakage): never
            # evidence of one bad rank — don't let it drive a shrink
            last_failed, consecutive = None, 0
        else:
            consecutive = (consecutive + 1 if failed_rank == last_failed
                           else 1)
            last_failed = failed_rank
        if (not preempted and args.elastic_min_nproc > 0 and consecutive >= 2
                and nproc - 1 >= args.elastic_min_nproc):
            # the same single rank twice in a row: continue smaller. Not
            # charged against --max-restarts — shrinks are bounded by
            # nproc − min on their own.
            nproc -= 1
            last_failed, consecutive = None, 0
            print(f"[run] rank {failed_rank} {why} twice; resizing group "
                  f"to {nproc} (elastic)", file=sys.stderr)
            continue
        if restarts >= args.max_restarts:
            print(f"[run] rank {failed_rank} {why}; no restarts left",
                  file=sys.stderr)
            # a preemption with no restart budget left still exits with
            # the distinct code so outer schedulers can tell reclaimed
            # capacity from a genuine failure
            return EXIT_PREEMPTED if preempted else 1
        restarts += 1
        if (args.elastic_min_nproc > 0 and nproc < args.nproc_per_node
                and healthy_for >= args.elastic_regrow_after):
            # regrow probe: the shrunken group ran healthy long enough
            # that this failure reads as an independent transient, and the
            # boundary tears the group down anyway — readmit one worker
            # toward the original size. Fast failures never reach here
            # (uptime gate), so shrink evidence for a still-bad slot keeps
            # accumulating instead of being reset by probes; flapping is
            # bounded because probes only ride charged restarts.
            nproc += 1
            last_failed, consecutive = None, 0
            print(f"[run] regrowing group to {nproc} (elastic probe "
                  f"toward {args.nproc_per_node})", file=sys.stderr)
        print(f"[run] rank {failed_rank} {why}; restart "
              f"{restarts}/{args.max_restarts}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
