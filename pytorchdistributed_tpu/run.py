"""torchrun-style launcher CLI with elastic restart.

    python -m pytorchdistributed_tpu.run --nproc-per-node 2 train.py --lr 3e-4

The agent process (this module) spawns one worker per rank with the env
contract the reference's scripts read (RANK / WORLD_SIZE / LOCAL_RANK /
MASTER_ADDR / MASTER_PORT — reference ddp_gpus_torchrun.py:14-19), watches
for failures, and on ``--max-restarts > 0`` tears the group down and
relaunches it — restart-from-checkpoint semantics (workers are expected to
resume via Trainer.fit(resume=True); SURVEY.md §5 "Failure detection /
elastic recovery").

``--heartbeat-timeout T`` adds *hung*-rank detection on top of exit
watching: a rank wedged in a collective (the NCCL-deadlock analog) never
exits, so the agent also tracks per-rank liveness files
(runtime/heartbeat.py; the Trainer beats at its device-sync points) and
treats a rank silent for more than T seconds as failed — kill the group,
relaunch if restarts remain.
"""

from __future__ import annotations

import argparse
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

from pytorchdistributed_tpu.runtime.heartbeat import (
    HEARTBEAT_DIR_ENV,
    stale_ranks,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_group(argv, nproc: int, port: int,
                 devices_per_proc: int | None,
                 heartbeat_dir: str | None = None) -> list[subprocess.Popen]:
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update({
            "RANK": str(rank),
            "LOCAL_RANK": str(rank),
            "WORLD_SIZE": str(nproc),
            "MASTER_ADDR": "localhost",
            "MASTER_PORT": str(port),
        })
        if heartbeat_dir is not None:
            env[HEARTBEAT_DIR_ENV] = heartbeat_dir
        if devices_per_proc is not None:
            from pytorchdistributed_tpu.runtime.launch import sim_device_flags
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = sim_device_flags(
                env.get("XLA_FLAGS", ""), devices_per_proc)
        procs.append(subprocess.Popen([sys.executable] + argv, env=env))
    return procs


def _kill_group(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
            # a SIGSTOPped (hung-and-frozen) worker can't handle SIGTERM;
            # wake it so termination isn't stuck behind the 10s escalation
            p.send_signal(signal.SIGCONT)
    deadline = time.time() + 10
    for p in procs:
        try:
            p.wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        "pytorchdistributed_tpu.run",
        description="torchrun-equivalent launcher "
                    "(reference ddp_gpus_torchrun.py:102)")
    parser.add_argument("--nproc-per-node", type=int, default=1)
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="relaunch the whole group this many times if a "
                             "rank fails (workers resume from checkpoints)")
    parser.add_argument("--monitor-interval", type=float, default=0.2)
    parser.add_argument("--heartbeat-timeout", type=float, default=0.0,
                        help="seconds of per-rank heartbeat silence before "
                             "the group counts as hung and is relaunched "
                             "(0 = exit-watching only)")
    parser.add_argument("--heartbeat-grace", type=float, default=300.0,
                        help="extra allowance before a rank's FIRST beat "
                             "(imports + first XLA compile)")
    parser.add_argument("--devices-per-proc", type=int, default=None,
                        help="CPU-sim chips per process (sets JAX_PLATFORMS="
                             "cpu + xla_force_host_platform_device_count)")
    parser.add_argument("script", help="training script to run")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    worker_argv = [args.script] + args.script_args
    restarts = 0
    while True:
        port = _free_port()
        # fresh heartbeat dir per incarnation: a relaunch must not inherit
        # the dead group's file mtimes
        hb_dir = (tempfile.mkdtemp(prefix="ptd_heartbeat_")
                  if args.heartbeat_timeout > 0 else None)
        spawned_at = time.time()
        procs = _spawn_group(worker_argv, args.nproc_per_node, port,
                             args.devices_per_proc, hb_dir)
        failed_rank, why = None, "failed"
        while failed_rank is None:
            time.sleep(args.monitor_interval)
            codes = [p.poll() for p in procs]
            if any(c not in (None, 0) for c in codes):
                failed_rank = codes.index(
                    next(c for c in codes if c not in (None, 0)))
            elif all(c == 0 for c in codes):
                if hb_dir is not None:
                    shutil.rmtree(hb_dir, ignore_errors=True)
                return 0
            elif hb_dir is not None:
                hung = stale_ranks(hb_dir, args.nproc_per_node,
                                   timeout=args.heartbeat_timeout,
                                   grace=args.heartbeat_grace,
                                   now=time.time(), baseline=spawned_at)
                # only live ranks count as hung — a cleanly-exited rank
                # stops beating legitimately while the rest finish up
                hung = [r for r in hung if codes[r] is None]
                if hung:
                    failed_rank, why = hung[0], "hung (heartbeat stale)"
        _kill_group(procs)
        if hb_dir is not None:  # each incarnation gets a fresh dir
            shutil.rmtree(hb_dir, ignore_errors=True)
        if restarts >= args.max_restarts:
            print(f"[run] rank {failed_rank} {why}; no restarts left",
                  file=sys.stderr)
            return 1
        restarts += 1
        print(f"[run] rank {failed_rank} {why}; restart "
              f"{restarts}/{args.max_restarts}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
