"""Autoregressive generation with a KV cache.

The reference's only inference ambition is the llama-7b
`device_map="auto"` cell (reference 03_model_parallel.ipynb:86-89), which
never ran. This is the TPU-native realization: a jitted `lax.scan` decode
loop over the model's "cache" collection (TransformerConfig(decode=True) —
each attention layer keeps a [b, max_seq_len, kv_heads, head_dim] K/V cache
updated in place per step), with greedy / temperature / top-k sampling.

Design notes (XLA semantics):
  * the whole generate call is ONE compiled program — a single chunked
    prefill forward fills the cache over the whole prompt, then a
    `lax.scan` emits one token per tick; no per-token dispatch from Python;
  * static shapes: the cache is allocated at `max_seq_len` up front and the
    scan always runs `max_new_tokens` ticks; stop ids freeze finished rows
    (they keep emitting the pad/stop id) instead of exiting early;
  * sharding: params may be sharded (dp/tp rules) — the decode einsums
    partition the same way the training ones do; generate runs under
    whatever mesh the params live on;
  * retrace control: every distinct (prompt_len, max_new_tokens) pair is a
    distinct compiled program; `generate_bucketed` pads both up to
    128-lane buckets so variable-length traffic hits a handful of programs
    (TRACE_COUNTS is the regression counter the tests pin).

The sampling helpers (`_sample` for batch-uniform params,
`sample_slots` for the per-row vectorized variant) and the
`attend_window` cache-window rule are shared with the continuous-batching
serving engine (serving/).
"""

from __future__ import annotations

import collections
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Traced-body invocation counter, keyed by program name: the python body
# of a jitted function runs only when jax actually (re)traces it, so this
# is the retrace tripwire the bucketing tests pin (a cache hit never
# touches it).
TRACE_COUNTS: collections.Counter = collections.Counter()


def attend_window(max_seq_len: int, total: int, lanes: int = 128) -> int:
    """The decode-time attention window for a generation reaching ``total``
    tokens: 128-lane-rounded, clamped to the model's context. Shared by
    generate() and the serving engine so both bound per-tick score work
    the same way."""
    return min(max_seq_len, -(-total // lanes) * lanes)


def stop_ids_tuple(eos_id) -> tuple[int, ...]:
    """Normalize the ``eos_id`` argument (None | int | sequence of ints) to
    the static tuple the jitted programs hash on. Tokenizers commonly have
    several stop ids (e.g. <|eot_id|> and <|end_of_text|>); any of them
    freezes a row, and frozen rows keep emitting the FIRST id as pad."""
    if eos_id is None:
        return ()
    if isinstance(eos_id, (int, np.integer)):
        return (int(eos_id),)
    return tuple(int(e) for e in eos_id)


def matches_stop(tok, stop_ids: tuple[int, ...]):
    """[b] bool: does each token match any of the (static) stop ids?"""
    if not stop_ids:
        return jnp.zeros(tok.shape, bool)
    hit = tok == stop_ids[0]
    for s in stop_ids[1:]:
        hit = hit | (tok == s)
    return hit


def _sample(logits, key, *, temperature: float, top_k: int | None,
            top_p: float | None = None, top_p_candidates: int = 256):
    """One sampling step over [b, vocab] fp32 logits (batch-uniform
    params — every row shares temperature/top_k/top_p; the per-row
    variant is sample_slots)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_p is not None:
        # Nucleus sampling over the top-C candidates (C = top_k or
        # top_p_candidates): a full-vocab descending sort costs ~100x per
        # tick on v5e at vocab 50k, and in practice the p-mass lives far
        # inside the top 256. For flat/high-temperature distributions
        # where the true nucleus may be wider, raise top_p_candidates
        # (vocab_size recovers exact nucleus sampling). Drop candidates
        # once the cumulative probability BEFORE them reaches p (the
        # first token always survives); the retained mass is
        # renormalized over the candidate set.
        c = min(top_k or top_p_candidates, logits.shape[-1])
        vals, idxs = lax.top_k(logits, c)  # descending
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        vals = jnp.where(cum >= top_p, -jnp.inf, vals)
        choice = jax.random.categorical(key, vals, axis=-1)
        return jnp.take_along_axis(
            idxs, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
    if top_k is not None:
        # lax.top_k, not a full-vocab sort: measured ~100x per-tick win on
        # v5e at vocab 50k
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _slot_candidates(logits, temperature, top_k, top_p, candidates: int):
    """The shared per-row candidate filter behind ``sample_slots`` and
    ``slot_filtered_probs``: top-``candidates`` logits per row, rank-masked
    by the dynamic per-row top_k, temperature-scaled, nucleus-masked
    (drop candidates once the cumulative probability BEFORE them reaches
    p — the first candidate always survives, same rule as _sample).
    Returns ``(vals, idxs)``: [n, c] filtered/scaled logits (-inf at
    dropped candidates) and their vocab ids. One function so the sampler
    and the speculative-decoding probability vectors can never drift
    apart — losslessness of the rejection kernel depends on q/p being
    EXACTLY the distributions the sampler draws from."""
    c = min(candidates, logits.shape[-1])
    vals, idxs = lax.top_k(logits, c)            # [n, c] descending
    k = jnp.where(top_k > 0, jnp.minimum(top_k, c), c)
    vals = jnp.where(jnp.arange(c)[None, :] < k[:, None], vals, -jnp.inf)
    vals = vals / jnp.maximum(temperature, 1e-6)[:, None]
    probs = jax.nn.softmax(vals, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs
    vals = jnp.where(cum >= top_p[:, None], -jnp.inf, vals)
    return vals, idxs


def sample_slots(logits, keys, temperature, top_k, top_p, *,
                 candidates: int = 64):
    """Per-row sampling over ``[n, vocab]`` fp32 logits where every row
    carries its OWN (dynamic) sampling params — the serving engine's one
    compiled sampler for any mix of requests.

      keys:        [n] typed PRNG keys (one stream per request).
      temperature: [n] f32; <= 0 means greedy for that row.
      top_k:       [n] i32; <= 0 disables (row keeps all candidates).
      top_p:       [n] f32; >= 1 disables.
      candidates:  static candidate-set width C — per-row top_k is a rank
        mask over the shared lax.top_k(C) prefix (a dynamic per-row k
        cannot be a static top_k argument), so effective top_k caps at C.

    Greedy rows take idxs[:, 0] == argmax (lax.top_k is index-stable), so
    a temperature-0 row is bitwise `jnp.argmax` — the parity property the
    serving tests pin against generate()."""
    vals, idxs = _slot_candidates(logits, temperature, top_k, top_p,
                                  candidates)
    greedy = idxs[:, 0]
    choice = jax.vmap(jax.random.categorical)(keys, vals)
    sampled = jnp.take_along_axis(idxs, choice[:, None], axis=-1)[:, 0]
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def slot_filtered_probs(logits, temperature, top_k, top_p, *,
                        candidates: int = 64):
    """Full-vocab probability vectors ``[n, vocab]`` of the EXACT per-row
    distribution ``sample_slots`` draws from (same candidate filter, same
    renormalization — they share `_slot_candidates`). Greedy rows
    (temperature <= 0) return an exact one-hot at idxs[:, 0] == argmax,
    so rejection sampling against these vectors degenerates to
    accept-iff-argmax-matches — the bitwise-greedy property the
    speculative tests pin. The speculative decoder's q (draft) and p
    (target) are both computed here."""
    n, v = logits.shape
    vals, idxs = _slot_candidates(logits, temperature, top_k, top_p,
                                  candidates)
    probs = jax.nn.softmax(vals, axis=-1)        # 0 at dropped candidates
    rows = jnp.arange(n)[:, None]
    full = jnp.zeros((n, v), jnp.float32).at[rows, idxs].set(probs)
    onehot = jnp.zeros((n, v), jnp.float32).at[
        jnp.arange(n), idxs[:, 0]].set(1.0)
    return jnp.where((temperature <= 0.0)[:, None], onehot, full)


def speculative_accept(draft_tokens, q_probs, p_probs, unif, res_keys,
                       greedy, k_eff=None):
    """Vectorized lossless rejection sampling (Leviathan et al. 2023;
    Chen et al. 2023): decide, per row, how many draft proposals the
    target model keeps, and sample the one correction/bonus token that
    follows — the emitted tokens are distributed EXACTLY as if the target
    had sampled them one by one.

      draft_tokens: [n, k] draft proposals.
      q_probs:      [n, k, vocab] the draft distributions each proposal
        was sampled from (slot_filtered_probs of the draft logits).
      p_probs:      [n, k+1, vocab] target distributions at every
        position of the verify forward (position i scores the token
        AFTER draft_tokens[:, :i]).
      unif:         [n, k] uniforms in [0, 1) — the accept coin flips.
      res_keys:     [n] PRNG keys for the residual/bonus sample.
      greedy:       [n] bool — rows whose correction must be the exact
        argmax (their p/q are one-hots, so acceptance is deterministic
        and no randomness is consumed).

    Proposal i is accepted with probability min(1, p_i(x_i)/q_i(x_i));
    the first rejection at position i resamples from the residual
    norm(max(p_i - q_i, 0)), and a fully-accepted row draws a BONUS
    token from p_{k+1} — the q=0 degenerate of the same residual formula.
    Returns ``(tokens [n, k+1], n_accept [n])``: tokens[:, :n_accept] are
    the kept proposals and tokens[:, n_accept] the correction/bonus; the
    caller reads exactly n_accept+1 tokens per row (later positions hold
    leftover proposals).

    ``k_eff`` (optional [n] int32 in [1, k]) is the per-row EFFECTIVE
    proposal depth — adaptive k (ISSUE 16) as a masked width inside the
    fixed k-wide program, so a per-slot depth change never retraces.
    Proposals at positions >= a row's k_eff are treated as never made:
    acceptance stops there, and a row that accepts all k_eff proposals
    draws its bonus from the FULL target distribution at position k_eff
    (q forced to 0 — that position's proposal was not offered, so the
    rejection-resample residual would be the wrong measure). The emitted
    prefix stays exactly target-distributed for every k_eff; greedy rows
    are bitwise-invariant to it (the correction is argmax(p) either
    way)."""
    n, k = draft_tokens.shape
    rows = jnp.arange(n)
    p_at = jnp.take_along_axis(
        p_probs[:, :k], draft_tokens[..., None], axis=-1)[..., 0]
    q_at = jnp.take_along_axis(
        q_probs, draft_tokens[..., None], axis=-1)[..., 0]
    # u < min(1, p/q)  <=>  u*q < p for u in [0,1): no division, and the
    # greedy one-hot case stays exact (q_at == 1.0 exactly)
    accept = unif * q_at < p_at                              # [n, k]
    if k_eff is not None:
        accept = accept & (jnp.arange(k)[None, :] < k_eff[:, None])
    n_accept = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)
    p_cut = p_probs[rows, n_accept]                          # [n, vocab]
    lim = k if k_eff is None else jnp.minimum(k_eff, k)
    q_cut = jnp.where((n_accept < lim)[:, None],
                      q_probs[rows, jnp.minimum(n_accept, k - 1)], 0.0)
    res = jnp.maximum(p_cut - q_cut, 0.0)
    tot = res.sum(axis=-1, keepdims=True)
    # a rejection with p <= q everywhere is impossible in exact math but
    # can appear under fp rounding: fall back to the target distribution
    res = jnp.where(tot > 0, res / jnp.where(tot > 0, tot, 1.0), p_cut)
    sampled = jax.vmap(jax.random.categorical)(res_keys, jnp.log(res))
    corr = jnp.where(greedy, jnp.argmax(p_cut, axis=-1),
                     sampled).astype(jnp.int32)
    out = jnp.concatenate(
        [draft_tokens, jnp.zeros((n, 1), jnp.int32)], axis=1)
    out = jnp.where(jnp.arange(k + 1)[None, :] == n_accept[:, None],
                    corr[:, None], out)
    return out, n_accept


def reset_cache_positions(cache, new_index):
    """Set every position counter in a decode cache collection ("index"
    per attention layer, "pos_index" in the embedder) to ``new_index`` —
    the bucketing trick: after a PADDED prefill advanced the counters to
    the bucket length, rewind them to the true prompt length so decode
    overwrites the pad rows (which the position mask keeps unattendable
    until then). ``new_index`` may be a scalar or, for a slot-decode
    (``decode_slots > 0``) cache, a per-row [slots] vector — the
    speculative decoder rewinds each row to its OWN accepted length this
    way (scanned-layer counter leaves are [L, slots]; the vector
    broadcasts up the scan axis)."""
    def fix(path, leaf):
        name = getattr(path[-1], "key", str(path[-1]))
        if name in ("index", "pos_index"):
            return jnp.broadcast_to(new_index, leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def kv_cache_bytes(cache) -> int:
    """HBM bytes of a decode cache collection's K/V payload (dense rows
    or the paged block pool — the counter/table leaves are noise).
    Includes the int8 pool's fp32 scale planes: they are real HBM the
    compressed pool pays, so "same HBM budget" A/Bs charge for them.
    Shared by the serving engine's summary and bench.py's paged-capacity
    A/B, so both sides of every "same HBM budget" claim are measured by
    the one function."""
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        name = getattr(path[-1], "key", str(path[-1]))
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def _zero_cache(model, prompt):
    """A fresh all-zero cache collection for ``model`` at ``prompt``'s
    batch size (shapes via eval_shape — nothing is initialized)."""
    cache = jax.eval_shape(
        lambda: model.init(jax.random.key(0), prompt[:, :1])["cache"])
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache)


def _decode_ticks(model, weights, cache, first, rng, done, *, length,
                  temperature, top_k, top_p, top_p_candidates, eos_ids):
    """The shared decode loop: ``length`` single-token ticks from ``first``
    under a lax.scan. Returns [b, length] sampled tokens (frozen rows
    emit the first stop id)."""
    def tick(carry, _):
        cache, tok, key, done = carry
        logits, mut = model.apply(
            {"params": weights, "cache": cache}, tok[:, None],
            mutable=["cache"])
        key, sub = jax.random.split(key)
        nxt = _sample(logits[:, 0].astype(jnp.float32), sub,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      top_p_candidates=top_p_candidates)
        if eos_ids:
            nxt = jnp.where(done, eos_ids[0], nxt)
            done = done | matches_stop(nxt, eos_ids)
        return (mut["cache"], nxt, key, done), nxt

    (_, _, _, _), toks = lax.scan(
        tick, (cache, first, rng, done), None, length=length)
    return toks.T.astype(jnp.int32)


def _windowed(model, total: int):
    """Clone ``model`` with the decode attention window bounded to the
    slots this generation can actually reach (128-lane-rounded): at long
    max_seq_len with a short generation the dense-over-whole-cache score
    work is almost all waste."""
    cfg = model.cfg
    attend = attend_window(cfg.max_seq_len, total)
    if (cfg.decode_attend_len or cfg.max_seq_len) != attend:
        model = model.clone(
            cfg=dataclasses.replace(cfg, decode_attend_len=attend))
    return model


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "top_p_candidates", "eos_ids"))
def generate_jit(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    top_p_candidates: int = 256,
    eos_ids: tuple[int, ...] = (),
    rng=None,
):
    """The jitted body behind generate() (stop ids pre-normalized to a
    static tuple). Prefer generate(); this is exposed for AOT lowering
    (tests/test_compiled_invariants.decode_lowered)."""
    TRACE_COUNTS["generate"] += 1
    if rng is None:  # same default as generate() (unused when greedy)
        rng = jax.random.key(0)
    b, prompt_len = prompt.shape
    model = _windowed(model, prompt_len + max_new_tokens)
    cache = _zero_cache(model, prompt)
    weights = params["params"] if "params" in params else params

    # Chunked prefill: ONE apply over the whole prompt fills every layer's
    # cache and yields the logits for the first new token — prompt cost is
    # a single parallel forward, not prompt_len sequential ticks.
    logits, mut = model.apply(
        {"params": weights, "cache": cache}, prompt, mutable=["cache"])
    rng, sub = jax.random.split(rng)
    first = _sample(logits[:, -1].astype(jnp.float32), sub,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    top_p_candidates=top_p_candidates)
    done = matches_stop(first, eos_ids)
    toks = _decode_ticks(model, weights, mut["cache"], first, rng, done,
                         length=max_new_tokens - 1, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         top_p_candidates=top_p_candidates, eos_ids=eos_ids)
    return jnp.concatenate([prompt, first[:, None], toks], axis=1)


def _validate(model, prompt_len: int, max_new_tokens: int) -> None:
    cfg = model.cfg
    if not cfg.decode:
        raise ValueError(
            "generate() needs a decode-mode model: build it with "
            "TransformerConfig(decode=True) / *_config(..., decode=True)")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if prompt_len + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_seq_len {cfg.max_seq_len}")


def generate(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    top_p_candidates: int = 256,
    eos_id=None,
    rng=None,
):
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Args:
      model: a causal LM module built with ``decode=True`` in its config
        (GPT2 / Llama). ``cfg.max_seq_len`` bounds prompt + new tokens.
      params: the trained variables (``{"params": ...}``), same tree as the
        decode=False model — training params load unmodified.
      prompt: int32 ``[batch, prompt_len]`` token ids (prompt_len ≥ 1).
      temperature: 0 = greedy argmax; otherwise softmax temperature.
      top_k: restrict sampling to the k highest-logit tokens.
      top_p: nucleus sampling — keep the smallest candidate set with
        cumulative probability >= p (evaluated over the top-(top_k or
        top_p_candidates) candidates; see _sample). Composes with top_k.
      top_p_candidates: how many top logits nucleus sampling considers
        (default 256; set vocab_size for exact nucleus at full-sort cost —
        matters for flat/high-temperature distributions).
      eos_id: a stop id or a sequence of stop ids — rows that emit any of
        them freeze and keep emitting the first id (static-shape early
        stop).
      rng: PRNG key for sampling (defaults to key(0); unused when greedy).

    Returns int32 ``[batch, prompt_len + max_new_tokens]``: the prompt
    followed by the generated continuation.
    """
    _validate(model, prompt.shape[1], max_new_tokens)
    if rng is None:
        rng = jax.random.key(0)
    return generate_jit(model, params, prompt,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        top_p_candidates=top_p_candidates,
                        eos_ids=stop_ids_tuple(eos_id), rng=rng)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "top_p_candidates", "eos_ids"))
def _generate_padded(
    model,
    params,
    prompt,          # [b, padded_len] — true prompt in [:, :true_len]
    true_len,        # dynamic scalar: the unpadded prompt length
    *,
    max_new_tokens: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    top_p_candidates: int,
    eos_ids: tuple[int, ...],
    rng,
):
    """generate_jit over a right-padded prompt with a DYNAMIC true length:
    prefill runs at the (static) bucket length, then the cache position
    counters rewind to ``true_len`` so decode starts there — pad rows sit
    beyond every row's position mask until the ticks overwrite them.
    Returns [b, padded_len + max_new_tokens] (continuation starts at
    column padded_len)."""
    TRACE_COUNTS["generate_padded"] += 1
    b, padded_len = prompt.shape
    model = _windowed(model, padded_len + max_new_tokens)
    cache = _zero_cache(model, prompt)
    weights = params["params"] if "params" in params else params

    logits, mut = model.apply(
        {"params": weights, "cache": cache}, prompt, mutable=["cache"])
    cache = reset_cache_positions(mut["cache"], true_len)
    last = lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)[:, 0]
    rng, sub = jax.random.split(rng)
    first = _sample(last.astype(jnp.float32), sub,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    top_p_candidates=top_p_candidates)
    done = matches_stop(first, eos_ids)
    toks = _decode_ticks(model, weights, cache, first, rng, done,
                         length=max_new_tokens - 1, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         top_p_candidates=top_p_candidates, eos_ids=eos_ids)
    return jnp.concatenate([prompt, first[:, None], toks], axis=1)


def _round_up(n: int, q: int) -> int:
    return -(-n // q) * q


def generate_bucketed(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    top_p_candidates: int = 256,
    eos_id=None,
    rng=None,
    bucket: int = 128,
    pad_id: int = 0,
):
    """generate() behind a retrace-bucketing wrapper (thin, non-jit).

    generate()'s compiled program is keyed on the STATIC
    (prompt_len, max_new_tokens) pair, so variable-length traffic — a
    chat frontend, an eval harness — retraces per distinct shape. This
    wrapper pads the prompt up to a ``bucket``-multiple (true length rides
    along as a dynamic scalar) and rounds max_new_tokens up the same way
    (extra ticks cost compute, not correctness — the tail is sliced off),
    so repeated calls hit a handful of compiled programs. Greedy outputs
    are bitwise-equal to generate()'s: pad positions sit beyond the
    position mask until decode overwrites them, and masked attention
    contributes exact zeros. Falls back to exact generate() when the
    bucketed shapes cannot fit max_seq_len. TRACE_COUNTS["generate_padded"]
    counts the compiles (the regression test's tripwire)."""
    b, prompt_len = prompt.shape
    _validate(model, prompt_len, max_new_tokens)
    max_seq_len = model.cfg.max_seq_len
    padded_len = min(_round_up(prompt_len, bucket), max_seq_len)
    new_bucket = min(_round_up(max_new_tokens, bucket),
                     max_seq_len - padded_len)
    if padded_len < prompt_len or new_bucket < max_new_tokens:
        # bucketing can't fit the context — take the exact-shape program
        return generate(model, params, prompt,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        top_p_candidates=top_p_candidates, eos_id=eos_id,
                        rng=rng)
    if rng is None:
        rng = jax.random.key(0)
    padded = jnp.pad(prompt, ((0, 0), (0, padded_len - prompt_len)),
                     constant_values=pad_id)
    out = _generate_padded(model, params, padded,
                           jnp.asarray(prompt_len, jnp.int32),
                           max_new_tokens=new_bucket,
                           temperature=temperature, top_k=top_k, top_p=top_p,
                           top_p_candidates=top_p_candidates,
                           eos_ids=stop_ids_tuple(eos_id), rng=rng)
    return jnp.concatenate(
        [prompt, out[:, padded_len:padded_len + max_new_tokens]], axis=1)


# ---------------------------------------------------------------------------
# Speculative decoding (ISSUE 8): draft-and-verify with lossless rejection
# sampling. Decode is memory-bound — every tick streams the whole target
# model through HBM for ONE token — so a cheap draft proposes k tokens and
# the target scores all k+1 positions in ONE batched forward; the rejection
# kernel (speculative_accept) keeps a provably target-distributed prefix.
# Greedy outputs are BITWISE-equal to generate()'s (the kernel degenerates
# to accept-iff-argmax-matches); sampled outputs are distribution-equal.


def truncated_draft(model, params, num_layers: int):
    """(draft_model, draft_params) built by TRUNCATING the target to its
    first ``num_layers`` transformer blocks — embedder, final norm and LM
    head shared, so vocab/embedding shapes match by construction. A free
    draft for speculative decoding: no extra training, and correctness
    never depends on its quality (the rejection kernel is lossless); only
    the acceptance rate — and hence the speedup — does."""
    cfg = model.cfg
    if not 0 < num_layers < cfg.num_layers:
        raise ValueError(
            f"draft num_layers {num_layers} must be in "
            f"[1, {cfg.num_layers - 1}] (a strict truncation of the target)")
    p = params["params"] if "params" in params else params
    h = dict(p["h"])
    if cfg.scan_layers:
        # scan-stacked block leaves are [L, ...]: slice the layer axis
        h["block"] = jax.tree.map(lambda a: a[:num_layers], h["block"])
    else:
        for name in list(h):
            if (name.startswith("block_")
                    and int(name.rsplit("_", 1)[1]) >= num_layers):
                del h[name]
    out = dict(p)
    out["h"] = h
    draft = model.clone(cfg=dataclasses.replace(cfg, num_layers=num_layers))
    return draft, {"params": out}


def make_draft(model, params, *, num_layers: int | None = None,
               spec_heads: int = 0, seed: int = 0):
    """(draft_model, draft_params) for speculative decoding — the one
    constructor behind every draft shape (ISSUE 16): ``num_layers`` < the
    target's truncates the block stack (truncated_draft, the free warm
    init), None/equal keeps the full stack (self-draft-sized);
    ``spec_heads`` > 0 attaches that many multi-token proposal heads
    (models ProposalHeads), ZERO-initialized so at step 0 every head
    reproduces the base head's distribution exactly — init is
    deterministic whatever ``seed`` (kept for API symmetry). The result
    drops straight into generate_speculative / ServingEngine
    ``draft_config``/``draft_params``, and training/distill.py uses it as
    the student's warm start."""
    import flax.linen as nn

    from pytorchdistributed_tpu.models.transformer import ProposalHeads

    cfg = model.cfg
    if num_layers is None or num_layers == cfg.num_layers:
        draft = model
        dparams = {"params": params["params"] if "params" in params
                   else params}
    else:
        draft, dparams = truncated_draft(model, params, num_layers)
    if spec_heads:
        if spec_heads < 0:
            raise ValueError(f"spec_heads must be >= 0, got {spec_heads}")
        dcfg = dataclasses.replace(draft.cfg, spec_heads=spec_heads)
        draft = draft.clone(cfg=dcfg)
        head_tree = nn.meta.unbox(ProposalHeads(dcfg).init(
            jax.random.key(seed),
            jnp.zeros((1, dcfg.embed_dim), dcfg.dtype))["params"])
        p = dict(dparams["params"])
        p["heads"] = head_tree
        dparams = {"params": p}
    return draft, dparams


def _verify_chunk(model, weights, cache, tok, d_prop, q_probs, unif,
                  res_keys, temperature, top_k, top_p, *, spec_k: int,
                  candidates: int, k_eff=None):
    """The verify half of one speculative round — ONE target forward
    over [tok, d_1..d_k] plus the lossless rejection kernel. Shared by
    the sequential-rollout and head-parallel draft paths (ISSUE 16), so
    the losslessness-critical math exists exactly once whatever proposed
    the tokens. Returns ``(cache, emitted [n, spec_k+1], n_accept)``."""
    n = tok.shape[0]
    chunk = jnp.concatenate([tok[:, None], d_prop], axis=1)
    logits, mut = model.apply(
        {"params": weights, "cache": cache}, chunk, mutable=["cache"])
    flat = logits.reshape(n * (spec_k + 1), -1).astype(jnp.float32)

    def rep(a):
        return jnp.repeat(a, spec_k + 1, axis=0)

    p_probs = slot_filtered_probs(
        flat, rep(temperature), rep(top_k), rep(top_p),
        candidates=candidates).reshape(n, spec_k + 1, -1)
    emitted, n_accept = speculative_accept(
        d_prop, q_probs, p_probs, unif, res_keys, temperature <= 0.0,
        k_eff=k_eff)
    return mut["cache"], emitted, n_accept


def draft_and_verify(model, draft_model, weights, draft_weights, cache,
                     draft_cache, tok, draft_keys, unif, res_keys,
                     temperature, top_k, top_p, *, spec_k: int,
                     candidates: int, k_eff=None):
    """One draft-and-verify round over per-row decode state — the
    losslessness-critical core shared by generate_speculative and the
    serving engine's spec_decode_tick (they differ only in how caches
    persist and keys derive; this math must never fork).

    Rolls the draft ``spec_k + 1`` single-token steps from ``tok`` (k
    proposals, plus one extra step that only writes the last proposal's
    K/V so a fully-accepted row's next round attends a complete draft
    cache), scores all k+1 positions with ONE target forward over
    [tok, d_1..d_k], and rejection-samples per row. ``draft_keys`` is a
    [spec_k+1, n] key array (one stream per rollout step per row);
    ``unif`` [n, spec_k] are the accept coins, ``res_keys`` [n] the
    residual/bonus streams; ``k_eff`` (optional [n]) masks each row's
    effective proposal depth (see speculative_accept). Returns
    ``(cache, draft_cache, emitted [n, spec_k+1], n_accept [n])`` — the
    caller consumes exactly n_accept+1 tokens per row."""

    def dstep(carry, keys_j):
        dc, t = carry
        logits, mut = draft_model.apply(
            {"params": draft_weights, "cache": dc}, t[:, None],
            mutable=["cache"])
        lg = logits[:, 0].astype(jnp.float32)
        nxt = sample_slots(lg, keys_j, temperature, top_k, top_p,
                           candidates=candidates)
        q = slot_filtered_probs(lg, temperature, top_k, top_p,
                                candidates=candidates)
        return (mut["cache"], nxt), (nxt, q)

    (draft_cache, _), (dtoks, qs) = lax.scan(
        dstep, (draft_cache, tok), draft_keys)
    d_prop = dtoks[:spec_k].T                        # [n, k]
    q_probs = jnp.moveaxis(qs[:spec_k], 0, 1)        # [n, k, vocab]
    cache, emitted, n_accept = _verify_chunk(
        model, weights, cache, tok, d_prop, q_probs, unif, res_keys,
        temperature, top_k, top_p, spec_k=spec_k, candidates=candidates,
        k_eff=k_eff)
    return cache, draft_cache, emitted, n_accept


def draft_propose_heads(draft_model, draft_weights, draft_cache,
                        prev_tokens, prev_idx, draft_keys, temperature,
                        top_k, top_p, *, spec_k: int, candidates: int):
    """ONE head-parallel draft forward proposing all spec_k tokens
    (ISSUE 16, the Medusa shape): the draft processes ``prev_tokens`` —
    the PREVIOUS round's emitted buffer [n, spec_k+1], whose writes land
    at the caller-stamped draft positions and cover that round's
    rejected-suffix draft K/V (the same covering-writes property the
    target cache relies on) — reads the hidden state at each row's last
    live index ``prev_idx``, and samples proposal 1 from the base head
    and proposals 2..k from the multi-token heads, all conditioned on
    the same hidden state (head proposals are offset-specialized, not
    sequentially conditioned — the acceptance-for-latency trade).
    ``draft_keys`` is the SAME [spec_k+1, n] key array the sequential
    rollout consumes: proposal j samples with stream j either way.
    Returns ``(draft_cache, d_prop [n, k], q_probs [n, k, vocab])``."""
    n = prev_tokens.shape[0]
    hid, mut = draft_model.apply(
        {"params": draft_weights, "cache": draft_cache}, prev_tokens,
        method="hidden_states", mutable=["cache"])
    draft_cache = mut["cache"]
    hsel = jnp.take_along_axis(
        hid, prev_idx[:, None, None], axis=1)[:, 0]   # [n, embed]
    # the cache collection rides along read-only: decode-mode setup
    # declares position variables even on the projection-only methods
    base = draft_model.apply(
        {"params": draft_weights, "cache": draft_cache}, hsel,
        method="logits_from_hidden")
    heads = draft_model.apply(
        {"params": draft_weights, "cache": draft_cache}, hsel,
        method="head_logits")
    all_lg = jnp.concatenate(
        [base[:, None], heads[:, :spec_k - 1]],
        axis=1).astype(jnp.float32)                   # [n, k, vocab]
    flat = all_lg.reshape(n * spec_k, -1)

    def rep(a):
        return jnp.repeat(a, spec_k, axis=0)

    keys = jnp.swapaxes(draft_keys[:spec_k], 0, 1).reshape(n * spec_k)
    d_prop = sample_slots(flat, keys, rep(temperature), rep(top_k),
                          rep(top_p), candidates=candidates)
    q_probs = slot_filtered_probs(flat, rep(temperature), rep(top_k),
                                  rep(top_p), candidates=candidates)
    return (draft_cache, d_prop.reshape(n, spec_k),
            q_probs.reshape(n, spec_k, -1))


def draft_and_verify_heads(model, draft_model, weights, draft_weights,
                           cache, draft_cache, tok, prev_tokens, prev_idx,
                           draft_keys, unif, res_keys, temperature, top_k,
                           top_p, *, spec_k: int, candidates: int,
                           k_eff=None):
    """The head-parallel twin of draft_and_verify: the draft's k+1-step
    sequential rollout collapses to a single forward over the previous
    round's emitted buffer (draft_propose_heads), and the verify half is
    the SAME _verify_chunk — rejection kernel, covering-writes, and the
    no-rollback property are untouched, so losslessness never forks.
    Caller contract: ``draft_cache`` positions are stamped at the
    previous round's start (one round behind the target's), so this
    forward writes the emitted tokens' draft K/V exactly where the next
    round attends them."""
    draft_cache, d_prop, q_probs = draft_propose_heads(
        draft_model, draft_weights, draft_cache, prev_tokens, prev_idx,
        draft_keys, temperature, top_k, top_p, spec_k=spec_k,
        candidates=candidates)
    cache, emitted, n_accept = _verify_chunk(
        model, weights, cache, tok, d_prop, q_probs, unif, res_keys,
        temperature, top_k, top_p, spec_k=spec_k, candidates=candidates,
        k_eff=k_eff)
    return cache, draft_cache, emitted, n_accept


@functools.partial(
    jax.jit,
    static_argnames=("model", "draft_model", "spec_k", "max_new_tokens",
                     "temperature", "top_k", "top_p", "eos_ids",
                     "candidates"))
def _speculative_jit(model, draft_model, params, draft_params, prompt, rng,
                     *, spec_k: int, max_new_tokens: int, temperature: float,
                     top_k: int | None, top_p: float | None,
                     eos_ids: tuple[int, ...], candidates: int):
    """The jitted body behind generate_speculative: chunked prefill of
    BOTH caches, then a lax.while_loop of draft-and-verify rounds. Both
    models are slot-decode clones (``decode_slots == batch``) because
    per-row accepted lengths diverge — every round re-stamps the position
    counters from the per-row length vector (reset_cache_positions), so
    rejected-suffix K/V needs no rollback: the next round's k+1 writes
    land at [len, len+k] and always cover the stale region, and the
    position mask keeps anything beyond a row's length unattendable.

    When the draft carries proposal heads (cfg.spec_heads > 0, ISSUE 16)
    the carry gains the head-parallel round state — prev_toks (last
    round's emitted buffer, the NEXT draft forward's input chunk),
    prev_idx (each row's last live index in it) and prev_pos (the draft
    positions it writes at, one round behind the target's) — and the
    draft's sequential rollout becomes one forward; the verify half and
    everything below it are byte-for-byte the same code path."""
    TRACE_COUNTS["generate_speculative"] += 1
    heads_mode = draft_model.cfg.spec_heads > 0
    b, plen = prompt.shape
    weights = params["params"] if "params" in params else params
    dweights = (draft_params["params"] if "params" in draft_params
                else draft_params)
    temps = jnp.full((b,), temperature, jnp.float32)
    tks = jnp.full((b,), top_k or 0, jnp.int32)
    tps = jnp.full((b,), 1.0 if top_p is None else top_p, jnp.float32)

    t_cache = _zero_cache(model, prompt)
    d_cache = _zero_cache(draft_model, prompt)
    logits, mut = model.apply(
        {"params": weights, "cache": t_cache}, prompt, mutable=["cache"])
    t_cache = mut["cache"]
    _, dmut = draft_model.apply(
        {"params": dweights, "cache": d_cache}, prompt, mutable=["cache"])
    d_cache = dmut["cache"]

    rng, sub = jax.random.split(rng)
    first = sample_slots(logits[:, -1].astype(jnp.float32),
                         jax.random.split(sub, b), temps, tks, tps,
                         candidates=candidates)
    width = max_new_tokens + spec_k + 1
    out = jnp.zeros((b, width), jnp.int32).at[:, 0].set(first)
    n_out = jnp.ones((b,), jnp.int32)
    done = matches_stop(first, eos_ids) | (n_out >= max_new_tokens)
    pos = jnp.full((b,), plen, jnp.int32)

    def cond(carry):
        return jnp.any(~carry[5])

    def body(carry):
        if heads_mode:
            (t_cache, d_cache, out, n_out, tok, done, pos, key,
             prev_toks, prev_idx, prev_pos) = carry
        else:
            t_cache, d_cache, out, n_out, tok, done, pos, key = carry
        t_cache = reset_cache_positions(t_cache, pos)
        key, kd, ka, kr = jax.random.split(key, 4)
        draft_keys = jax.vmap(lambda kj: jax.random.split(kj, b))(
            jax.random.split(kd, spec_k + 1))
        unif = jax.random.uniform(ka, (b, spec_k))
        if heads_mode:
            # the draft writes last round's emitted buffer, so its
            # positions lag the target's by one round
            d_cache = reset_cache_positions(d_cache, prev_pos)
            t_cache, d_cache, emitted, n_acc = draft_and_verify_heads(
                model, draft_model, weights, dweights, t_cache, d_cache,
                tok, prev_toks, prev_idx, draft_keys, unif,
                jax.random.split(kr, b), temps, tks, tps,
                spec_k=spec_k, candidates=candidates)
        else:
            d_cache = reset_cache_positions(d_cache, pos)
            t_cache, d_cache, emitted, n_acc = draft_and_verify(
                model, draft_model, weights, dweights, t_cache, d_cache,
                tok, draft_keys, unif, jax.random.split(kr, b), temps,
                tks, tps, spec_k=spec_k, candidates=candidates)
        if eos_ids:
            # a stop id freezes the rest of the round: everything after
            # it emits the first stop id, exactly generate()'s frozen-row
            # padding
            hit = matches_stop(emitted, eos_ids)
            prior = jnp.cumsum(hit, axis=1) - hit > 0
            emitted = jnp.where(prior, eos_ids[0], emitted)

        def wrow(buf, vals, start, skip):
            return jnp.where(
                skip, buf, lax.dynamic_update_slice(buf, vals, (start,)))

        out = jax.vmap(wrow)(out, emitted, n_out, done)
        m_emit = n_acc + 1
        tok = jnp.where(done, tok, emitted[jnp.arange(b), n_acc])
        n_out = jnp.where(done, n_out, n_out + m_emit)
        new_done = done | (n_out >= max_new_tokens)
        if eos_ids:
            live = jnp.arange(spec_k + 1)[None, :] <= n_acc[:, None]
            new_done = new_done | (
                ~done & (matches_stop(emitted, eos_ids) & live).any(axis=1))
        if heads_mode:
            # next round's draft input: this round's emitted buffer,
            # whose row-0 token sits one past the pre-advance pos
            prev_toks = jnp.where(done[:, None], prev_toks, emitted)
            prev_idx = jnp.where(done, prev_idx, n_acc)
            prev_pos = jnp.where(done, prev_pos, pos + 1)
        # freeze pos at the pre-round value for rows that just finished:
        # live rows keep pos == plen + n_out - 1 <= plen + max_new - 2,
        # so verify writes never pass plen + max_new + spec_k - 2 (the
        # wrapper's validation slack)
        pos = jnp.where(new_done, pos, pos + m_emit)
        if heads_mode:
            return (t_cache, d_cache, out, n_out, tok, new_done, pos, key,
                    prev_toks, prev_idx, prev_pos)
        return (t_cache, d_cache, out, n_out, tok, new_done, pos, key)

    carry = (t_cache, d_cache, out, n_out, first, done, pos, rng)
    if heads_mode:
        # round 1's draft chunk: the first committed token plus padding
        # (index 0 is the only live position), written at the target's
        # current pos — the draft cache holds only the prompt so far
        prev_toks = jnp.zeros((b, spec_k + 1), jnp.int32).at[:, 0].set(first)
        carry = carry + (prev_toks, jnp.zeros((b,), jnp.int32), pos)
    fin = lax.while_loop(cond, body, carry)
    out, n_out = fin[2], fin[3]
    pad = eos_ids[0] if eos_ids else 0
    res = jnp.where(jnp.arange(width)[None, :] < n_out[:, None], out, pad)
    return jnp.concatenate([prompt, res[:, :max_new_tokens]], axis=1)


def generate_speculative(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    draft_model=None,
    draft_params=None,
    spec_k: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id=None,
    rng=None,
    candidates: int = 64,
):
    """generate() with draft-and-verify speculative decoding: ``spec_k``
    draft proposals per target forward, losslessly verified (Leviathan
    et al. 2023). Greedy output is BITWISE-equal to generate()'s; sampled
    output is distribution-equal (the tokens follow exactly the filtered
    target distribution sample_slots draws from, whatever the draft).

    Args beyond generate()'s:
      draft_model / draft_params: the proposer — any causal LM sharing
        the target's vocab (e.g. `truncated_draft(model, params, n)`).
        None self-drafts with the target itself (acceptance ~1: the
        correctness/plumbing configuration, not a speedup).
      spec_k: static draft length per round (0 falls back to generate()).
      candidates: the sampler's candidate-set width (see sample_slots) —
        spec and plain sampling share the same filtered distribution.

    Falls back to plain generate() when the context cannot absorb the
    verify overshoot (prompt + max_new + spec_k must fit max_seq_len:
    each round's k+1 verify writes may run past the budget before the
    accepted length is known — rejected-suffix K/V is never rolled back,
    just overwritten by the next round)."""
    _validate(model, prompt.shape[1], max_new_tokens)
    b, plen = prompt.shape
    kw = dict(max_new_tokens=max_new_tokens, temperature=temperature,
              top_k=top_k, top_p=top_p, eos_id=eos_id, rng=rng)
    if spec_k < 1 or plen + max_new_tokens + spec_k > model.cfg.max_seq_len:
        return generate(model, params, prompt, **kw)
    if draft_model is None:
        draft_model, draft_params = model, params
    if draft_params is None:
        raise ValueError("draft_model without draft_params — pass both "
                         "(truncated_draft() builds the pair)")
    if draft_model.cfg.vocab_size != model.cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft_model.cfg.vocab_size} != target vocab "
            f"{model.cfg.vocab_size} (the draft proposes target tokens)")
    if 0 < draft_model.cfg.spec_heads < spec_k - 1:
        raise ValueError(
            f"draft has {draft_model.cfg.spec_heads} proposal heads but "
            f"spec_k={spec_k} needs {spec_k - 1} (base head proposes token "
            f"1, head j token j+2; build the draft with make_draft("
            f"spec_heads=spec_k-1))")

    def slot_clone(m, seq_len):
        return m.clone(cfg=dataclasses.replace(
            m.cfg, decode=True, attention="dense", decode_attend_len=None,
            decode_slots=b, kv_block_size=0, kv_blocks=0,
            max_seq_len=seq_len))

    if rng is None:
        rng = jax.random.key(0)
    return _speculative_jit(
        slot_clone(model, model.cfg.max_seq_len),
        slot_clone(draft_model, model.cfg.max_seq_len),
        params, draft_params, prompt, rng, spec_k=spec_k,
        max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, eos_ids=stop_ids_tuple(eos_id),
        candidates=candidates)
