"""Autoregressive generation with a KV cache.

The reference's only inference ambition is the llama-7b
`device_map="auto"` cell (reference 03_model_parallel.ipynb:86-89), which
never ran. This is the TPU-native realization: a jitted `lax.scan` decode
loop over the model's "cache" collection (TransformerConfig(decode=True) —
each attention layer keeps a [b, max_seq_len, kv_heads, head_dim] K/V cache
updated in place per step), with greedy / temperature / top-k sampling.

Design notes (XLA semantics):
  * the whole generate call is ONE compiled program — a single chunked
    prefill forward fills the cache over the whole prompt, then a
    `lax.scan` emits one token per tick; no per-token dispatch from Python;
  * static shapes: the cache is allocated at `max_seq_len` up front and the
    scan always runs `max_new_tokens` ticks; `eos_id` freezes finished rows
    (they keep emitting `eos_id`) instead of exiting early;
  * sharding: params may be sharded (dp/tp rules) — the decode einsums
    partition the same way the training ones do; generate runs under
    whatever mesh the params live on.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _sample(logits, key, *, temperature: float, top_k: int | None,
            top_p: float | None = None, top_p_candidates: int = 256):
    """One sampling step over [b, vocab] fp32 logits."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_p is not None:
        # Nucleus sampling over the top-C candidates (C = top_k or
        # top_p_candidates): a full-vocab descending sort costs ~100x per
        # tick on v5e at vocab 50k, and in practice the p-mass lives far
        # inside the top 256. For flat/high-temperature distributions
        # where the true nucleus may be wider, raise top_p_candidates
        # (vocab_size recovers exact nucleus sampling). Drop candidates
        # once the cumulative probability BEFORE them reaches p (the
        # first token always survives); the retained mass is
        # renormalized over the candidate set.
        c = min(top_k or top_p_candidates, logits.shape[-1])
        vals, idxs = lax.top_k(logits, c)  # descending
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        vals = jnp.where(cum >= top_p, -jnp.inf, vals)
        choice = jax.random.categorical(key, vals, axis=-1)
        return jnp.take_along_axis(
            idxs, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
    if top_k is not None:
        # lax.top_k, not a full-vocab sort: measured ~100x per-tick win on
        # v5e at vocab 50k
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "top_p_candidates", "eos_id"))
def generate(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    top_p_candidates: int = 256,
    eos_id: int | None = None,
    rng=None,
):
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Args:
      model: a causal LM module built with ``decode=True`` in its config
        (GPT2 / Llama). ``cfg.max_seq_len`` bounds prompt + new tokens.
      params: the trained variables (``{"params": ...}``), same tree as the
        decode=False model — training params load unmodified.
      prompt: int32 ``[batch, prompt_len]`` token ids (prompt_len ≥ 1).
      temperature: 0 = greedy argmax; otherwise softmax temperature.
      top_k: restrict sampling to the k highest-logit tokens.
      top_p: nucleus sampling — keep the smallest candidate set with
        cumulative probability >= p (evaluated over the top-(top_k or
        top_p_candidates) candidates; see _sample). Composes with top_k.
      top_p_candidates: how many top logits nucleus sampling considers
        (default 256; set vocab_size for exact nucleus at full-sort cost —
        matters for flat/high-temperature distributions).
      eos_id: rows that emit it keep emitting it (static-shape early stop).
      rng: PRNG key for sampling (defaults to key(0); unused when greedy).

    Returns int32 ``[batch, prompt_len + max_new_tokens]``: the prompt
    followed by the generated continuation.
    """
    cfg = model.cfg
    if not cfg.decode:
        raise ValueError(
            "generate() needs a decode-mode model: build it with "
            "TransformerConfig(decode=True) / *_config(..., decode=True)")
    b, prompt_len = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    total = prompt_len + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_seq_len {cfg.max_seq_len}")
    if rng is None:
        rng = jax.random.key(0)

    # Bound per-tick attention to the slots this call can actually reach
    # (128-lane-rounded): at long max_seq_len with a short generation the
    # dense-over-whole-cache score work is almost all waste. Static under
    # this jit — prompt_len and max_new_tokens are already trace constants.
    import dataclasses

    attend = min(cfg.max_seq_len, -(-total // 128) * 128)
    if (cfg.decode_attend_len or cfg.max_seq_len) != attend:
        model = model.clone(
            cfg=dataclasses.replace(cfg, decode_attend_len=attend))

    cache = jax.eval_shape(
        lambda: model.init(jax.random.key(0), prompt[:, :1])["cache"])
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache)
    weights = params["params"] if "params" in params else params

    # Chunked prefill: ONE apply over the whole prompt fills every layer's
    # cache and yields the logits for the first new token — prompt cost is
    # a single parallel forward, not prompt_len sequential ticks.
    logits, mut = model.apply(
        {"params": weights, "cache": cache}, prompt, mutable=["cache"])
    cache = mut["cache"]
    rng, sub = jax.random.split(rng)
    first = _sample(logits[:, -1].astype(jnp.float32), sub,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    top_p_candidates=top_p_candidates)
    done = (first == eos_id) if eos_id is not None else jnp.zeros((b,), bool)

    def tick(carry, _):
        cache, tok, key, done = carry
        logits, mut = model.apply(
            {"params": weights, "cache": cache}, tok[:, None],
            mutable=["cache"])
        key, sub = jax.random.split(key)
        nxt = _sample(logits[:, 0].astype(jnp.float32), sub,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      top_p_candidates=top_p_candidates)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (mut["cache"], nxt, key, done), nxt

    (_, _, _, _), toks = lax.scan(
        tick, (cache, first, rng, done), None, length=max_new_tokens - 1)
    return jnp.concatenate(
        [prompt, first[:, None], toks.T.astype(jnp.int32)], axis=1)
