"""Autoregressive generation with a KV cache.

The reference's only inference ambition is the llama-7b
`device_map="auto"` cell (reference 03_model_parallel.ipynb:86-89), which
never ran. This is the TPU-native realization: a jitted `lax.scan` decode
loop over the model's "cache" collection (TransformerConfig(decode=True) —
each attention layer keeps a [b, max_seq_len, kv_heads, head_dim] K/V cache
updated in place per step), with greedy / temperature / top-k sampling.

Design notes (XLA semantics):
  * the whole generate call is ONE compiled program — a single chunked
    prefill forward fills the cache over the whole prompt, then a
    `lax.scan` emits one token per tick; no per-token dispatch from Python;
  * static shapes: the cache is allocated at `max_seq_len` up front and the
    scan always runs `max_new_tokens` ticks; stop ids freeze finished rows
    (they keep emitting the pad/stop id) instead of exiting early;
  * sharding: params may be sharded (dp/tp rules) — the decode einsums
    partition the same way the training ones do; generate runs under
    whatever mesh the params live on;
  * retrace control: every distinct (prompt_len, max_new_tokens) pair is a
    distinct compiled program; `generate_bucketed` pads both up to
    128-lane buckets so variable-length traffic hits a handful of programs
    (TRACE_COUNTS is the regression counter the tests pin).

The sampling helpers (`_sample` for batch-uniform params,
`sample_slots` for the per-row vectorized variant) and the
`attend_window` cache-window rule are shared with the continuous-batching
serving engine (serving/).
"""

from __future__ import annotations

import collections
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Traced-body invocation counter, keyed by program name: the python body
# of a jitted function runs only when jax actually (re)traces it, so this
# is the retrace tripwire the bucketing tests pin (a cache hit never
# touches it).
TRACE_COUNTS: collections.Counter = collections.Counter()


def attend_window(max_seq_len: int, total: int, lanes: int = 128) -> int:
    """The decode-time attention window for a generation reaching ``total``
    tokens: 128-lane-rounded, clamped to the model's context. Shared by
    generate() and the serving engine so both bound per-tick score work
    the same way."""
    return min(max_seq_len, -(-total // lanes) * lanes)


def stop_ids_tuple(eos_id) -> tuple[int, ...]:
    """Normalize the ``eos_id`` argument (None | int | sequence of ints) to
    the static tuple the jitted programs hash on. Tokenizers commonly have
    several stop ids (e.g. <|eot_id|> and <|end_of_text|>); any of them
    freezes a row, and frozen rows keep emitting the FIRST id as pad."""
    if eos_id is None:
        return ()
    if isinstance(eos_id, (int, np.integer)):
        return (int(eos_id),)
    return tuple(int(e) for e in eos_id)


def matches_stop(tok, stop_ids: tuple[int, ...]):
    """[b] bool: does each token match any of the (static) stop ids?"""
    if not stop_ids:
        return jnp.zeros(tok.shape, bool)
    hit = tok == stop_ids[0]
    for s in stop_ids[1:]:
        hit = hit | (tok == s)
    return hit


def _sample(logits, key, *, temperature: float, top_k: int | None,
            top_p: float | None = None, top_p_candidates: int = 256):
    """One sampling step over [b, vocab] fp32 logits (batch-uniform
    params — every row shares temperature/top_k/top_p; the per-row
    variant is sample_slots)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_p is not None:
        # Nucleus sampling over the top-C candidates (C = top_k or
        # top_p_candidates): a full-vocab descending sort costs ~100x per
        # tick on v5e at vocab 50k, and in practice the p-mass lives far
        # inside the top 256. For flat/high-temperature distributions
        # where the true nucleus may be wider, raise top_p_candidates
        # (vocab_size recovers exact nucleus sampling). Drop candidates
        # once the cumulative probability BEFORE them reaches p (the
        # first token always survives); the retained mass is
        # renormalized over the candidate set.
        c = min(top_k or top_p_candidates, logits.shape[-1])
        vals, idxs = lax.top_k(logits, c)  # descending
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        vals = jnp.where(cum >= top_p, -jnp.inf, vals)
        choice = jax.random.categorical(key, vals, axis=-1)
        return jnp.take_along_axis(
            idxs, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
    if top_k is not None:
        # lax.top_k, not a full-vocab sort: measured ~100x per-tick win on
        # v5e at vocab 50k
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_slots(logits, keys, temperature, top_k, top_p, *,
                 candidates: int = 64):
    """Per-row sampling over ``[n, vocab]`` fp32 logits where every row
    carries its OWN (dynamic) sampling params — the serving engine's one
    compiled sampler for any mix of requests.

      keys:        [n] typed PRNG keys (one stream per request).
      temperature: [n] f32; <= 0 means greedy for that row.
      top_k:       [n] i32; <= 0 disables (row keeps all candidates).
      top_p:       [n] f32; >= 1 disables.
      candidates:  static candidate-set width C — per-row top_k is a rank
        mask over the shared lax.top_k(C) prefix (a dynamic per-row k
        cannot be a static top_k argument), so effective top_k caps at C.

    Greedy rows take idxs[:, 0] == argmax (lax.top_k is index-stable), so
    a temperature-0 row is bitwise `jnp.argmax` — the parity property the
    serving tests pin against generate()."""
    c = min(candidates, logits.shape[-1])
    vals, idxs = lax.top_k(logits, c)            # [n, c] descending
    greedy = idxs[:, 0]
    k = jnp.where(top_k > 0, jnp.minimum(top_k, c), c)
    vals = jnp.where(jnp.arange(c)[None, :] < k[:, None], vals, -jnp.inf)
    vals = vals / jnp.maximum(temperature, 1e-6)[:, None]
    # nucleus: drop candidates once the cumulative probability BEFORE them
    # reaches p (first candidate always survives) — same rule as _sample
    probs = jax.nn.softmax(vals, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs
    vals = jnp.where(cum >= top_p[:, None], -jnp.inf, vals)
    choice = jax.vmap(jax.random.categorical)(keys, vals)
    sampled = jnp.take_along_axis(idxs, choice[:, None], axis=-1)[:, 0]
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def reset_cache_positions(cache, new_index):
    """Set every position counter in a decode cache collection ("index"
    per attention layer, "pos_index" in the embedder) to ``new_index`` —
    the bucketing trick: after a PADDED prefill advanced the counters to
    the bucket length, rewind them to the true prompt length so decode
    overwrites the pad rows (which the position mask keeps unattendable
    until then)."""
    def fix(path, leaf):
        name = getattr(path[-1], "key", str(path[-1]))
        if name in ("index", "pos_index"):
            return jnp.full_like(leaf, new_index)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def kv_cache_bytes(cache) -> int:
    """HBM bytes of a decode cache collection's K/V payload (dense rows
    or the paged block pool — the counter/table leaves are noise).
    Shared by the serving engine's summary and bench.py's paged-capacity
    A/B, so both sides of every "same HBM budget" claim are measured by
    the one function."""
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        name = getattr(path[-1], "key", str(path[-1]))
        if name in ("cached_key", "cached_value"):
            total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def _zero_cache(model, prompt):
    """A fresh all-zero cache collection for ``model`` at ``prompt``'s
    batch size (shapes via eval_shape — nothing is initialized)."""
    cache = jax.eval_shape(
        lambda: model.init(jax.random.key(0), prompt[:, :1])["cache"])
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache)


def _decode_ticks(model, weights, cache, first, rng, done, *, length,
                  temperature, top_k, top_p, top_p_candidates, eos_ids):
    """The shared decode loop: ``length`` single-token ticks from ``first``
    under a lax.scan. Returns [b, length] sampled tokens (frozen rows
    emit the first stop id)."""
    def tick(carry, _):
        cache, tok, key, done = carry
        logits, mut = model.apply(
            {"params": weights, "cache": cache}, tok[:, None],
            mutable=["cache"])
        key, sub = jax.random.split(key)
        nxt = _sample(logits[:, 0].astype(jnp.float32), sub,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      top_p_candidates=top_p_candidates)
        if eos_ids:
            nxt = jnp.where(done, eos_ids[0], nxt)
            done = done | matches_stop(nxt, eos_ids)
        return (mut["cache"], nxt, key, done), nxt

    (_, _, _, _), toks = lax.scan(
        tick, (cache, first, rng, done), None, length=length)
    return toks.T.astype(jnp.int32)


def _windowed(model, total: int):
    """Clone ``model`` with the decode attention window bounded to the
    slots this generation can actually reach (128-lane-rounded): at long
    max_seq_len with a short generation the dense-over-whole-cache score
    work is almost all waste."""
    cfg = model.cfg
    attend = attend_window(cfg.max_seq_len, total)
    if (cfg.decode_attend_len or cfg.max_seq_len) != attend:
        model = model.clone(
            cfg=dataclasses.replace(cfg, decode_attend_len=attend))
    return model


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "top_p_candidates", "eos_ids"))
def generate_jit(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    top_p_candidates: int = 256,
    eos_ids: tuple[int, ...] = (),
    rng=None,
):
    """The jitted body behind generate() (stop ids pre-normalized to a
    static tuple). Prefer generate(); this is exposed for AOT lowering
    (tests/test_compiled_invariants.decode_lowered)."""
    TRACE_COUNTS["generate"] += 1
    if rng is None:  # same default as generate() (unused when greedy)
        rng = jax.random.key(0)
    b, prompt_len = prompt.shape
    model = _windowed(model, prompt_len + max_new_tokens)
    cache = _zero_cache(model, prompt)
    weights = params["params"] if "params" in params else params

    # Chunked prefill: ONE apply over the whole prompt fills every layer's
    # cache and yields the logits for the first new token — prompt cost is
    # a single parallel forward, not prompt_len sequential ticks.
    logits, mut = model.apply(
        {"params": weights, "cache": cache}, prompt, mutable=["cache"])
    rng, sub = jax.random.split(rng)
    first = _sample(logits[:, -1].astype(jnp.float32), sub,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    top_p_candidates=top_p_candidates)
    done = matches_stop(first, eos_ids)
    toks = _decode_ticks(model, weights, mut["cache"], first, rng, done,
                         length=max_new_tokens - 1, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         top_p_candidates=top_p_candidates, eos_ids=eos_ids)
    return jnp.concatenate([prompt, first[:, None], toks], axis=1)


def _validate(model, prompt_len: int, max_new_tokens: int) -> None:
    cfg = model.cfg
    if not cfg.decode:
        raise ValueError(
            "generate() needs a decode-mode model: build it with "
            "TransformerConfig(decode=True) / *_config(..., decode=True)")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if prompt_len + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_seq_len {cfg.max_seq_len}")


def generate(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    top_p_candidates: int = 256,
    eos_id=None,
    rng=None,
):
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Args:
      model: a causal LM module built with ``decode=True`` in its config
        (GPT2 / Llama). ``cfg.max_seq_len`` bounds prompt + new tokens.
      params: the trained variables (``{"params": ...}``), same tree as the
        decode=False model — training params load unmodified.
      prompt: int32 ``[batch, prompt_len]`` token ids (prompt_len ≥ 1).
      temperature: 0 = greedy argmax; otherwise softmax temperature.
      top_k: restrict sampling to the k highest-logit tokens.
      top_p: nucleus sampling — keep the smallest candidate set with
        cumulative probability >= p (evaluated over the top-(top_k or
        top_p_candidates) candidates; see _sample). Composes with top_k.
      top_p_candidates: how many top logits nucleus sampling considers
        (default 256; set vocab_size for exact nucleus at full-sort cost —
        matters for flat/high-temperature distributions).
      eos_id: a stop id or a sequence of stop ids — rows that emit any of
        them freeze and keep emitting the first id (static-shape early
        stop).
      rng: PRNG key for sampling (defaults to key(0); unused when greedy).

    Returns int32 ``[batch, prompt_len + max_new_tokens]``: the prompt
    followed by the generated continuation.
    """
    _validate(model, prompt.shape[1], max_new_tokens)
    if rng is None:
        rng = jax.random.key(0)
    return generate_jit(model, params, prompt,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        top_p_candidates=top_p_candidates,
                        eos_ids=stop_ids_tuple(eos_id), rng=rng)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "top_p_candidates", "eos_ids"))
def _generate_padded(
    model,
    params,
    prompt,          # [b, padded_len] — true prompt in [:, :true_len]
    true_len,        # dynamic scalar: the unpadded prompt length
    *,
    max_new_tokens: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    top_p_candidates: int,
    eos_ids: tuple[int, ...],
    rng,
):
    """generate_jit over a right-padded prompt with a DYNAMIC true length:
    prefill runs at the (static) bucket length, then the cache position
    counters rewind to ``true_len`` so decode starts there — pad rows sit
    beyond every row's position mask until the ticks overwrite them.
    Returns [b, padded_len + max_new_tokens] (continuation starts at
    column padded_len)."""
    TRACE_COUNTS["generate_padded"] += 1
    b, padded_len = prompt.shape
    model = _windowed(model, padded_len + max_new_tokens)
    cache = _zero_cache(model, prompt)
    weights = params["params"] if "params" in params else params

    logits, mut = model.apply(
        {"params": weights, "cache": cache}, prompt, mutable=["cache"])
    cache = reset_cache_positions(mut["cache"], true_len)
    last = lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)[:, 0]
    rng, sub = jax.random.split(rng)
    first = _sample(last.astype(jnp.float32), sub,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    top_p_candidates=top_p_candidates)
    done = matches_stop(first, eos_ids)
    toks = _decode_ticks(model, weights, cache, first, rng, done,
                         length=max_new_tokens - 1, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         top_p_candidates=top_p_candidates, eos_ids=eos_ids)
    return jnp.concatenate([prompt, first[:, None], toks], axis=1)


def _round_up(n: int, q: int) -> int:
    return -(-n // q) * q


def generate_bucketed(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    top_p_candidates: int = 256,
    eos_id=None,
    rng=None,
    bucket: int = 128,
    pad_id: int = 0,
):
    """generate() behind a retrace-bucketing wrapper (thin, non-jit).

    generate()'s compiled program is keyed on the STATIC
    (prompt_len, max_new_tokens) pair, so variable-length traffic — a
    chat frontend, an eval harness — retraces per distinct shape. This
    wrapper pads the prompt up to a ``bucket``-multiple (true length rides
    along as a dynamic scalar) and rounds max_new_tokens up the same way
    (extra ticks cost compute, not correctness — the tail is sliced off),
    so repeated calls hit a handful of compiled programs. Greedy outputs
    are bitwise-equal to generate()'s: pad positions sit beyond the
    position mask until decode overwrites them, and masked attention
    contributes exact zeros. Falls back to exact generate() when the
    bucketed shapes cannot fit max_seq_len. TRACE_COUNTS["generate_padded"]
    counts the compiles (the regression test's tripwire)."""
    b, prompt_len = prompt.shape
    _validate(model, prompt_len, max_new_tokens)
    max_seq_len = model.cfg.max_seq_len
    padded_len = min(_round_up(prompt_len, bucket), max_seq_len)
    new_bucket = min(_round_up(max_new_tokens, bucket),
                     max_seq_len - padded_len)
    if padded_len < prompt_len or new_bucket < max_new_tokens:
        # bucketing can't fit the context — take the exact-shape program
        return generate(model, params, prompt,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        top_p_candidates=top_p_candidates, eos_id=eos_id,
                        rng=rng)
    if rng is None:
        rng = jax.random.key(0)
    padded = jnp.pad(prompt, ((0, 0), (0, padded_len - prompt_len)),
                     constant_values=pad_id)
    out = _generate_padded(model, params, padded,
                           jnp.asarray(prompt_len, jnp.int32),
                           max_new_tokens=new_bucket,
                           temperature=temperature, top_k=top_k, top_p=top_p,
                           top_p_candidates=top_p_candidates,
                           eos_ids=stop_ids_tuple(eos_id), rng=rng)
    return jnp.concatenate(
        [prompt, out[:, padded_len:padded_len + max_new_tokens]], axis=1)
