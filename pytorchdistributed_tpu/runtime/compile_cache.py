"""Persistent AOT executable cache — seconds-scale restart for every
relaunch path (ROADMAP item 5; the elastic half of ISSUE 10).

Every recovery mechanism this repo already has — crash/preempt restarts
(PR 4), replica failover (PR 9), new replicas joining a fleet — pays
full retrace + XLA compile on the way back up: a "recovered" process is
minutes away from its first token/step. The torchrun elastic-agent
contract this repo reproduces assumes relaunch is CHEAP; this module is
what makes that true on the XLA side:

  * programs are compiled **ahead of time** (``jit_fn.lower(...)
    .compile()`` — the same pjit ``Lowered``/``Compiled`` stages the
    compiled-invariant pins already read) and the executables
    **serialized to disk** (`jax.experimental.serialize_executable`);
  * entries are keyed by everything that could invalidate them —
    jax/jaxlib version, backend + topology fingerprint, program name,
    a caller config hash, the donation signature, and the full
    avals/shardings signature of the example arguments — so a wrong
    hit is structurally impossible: anything that would change the
    program changes the key;
  * each entry carries a sha256 **manifest** (the checkpoint-manifest
    style of training/checkpoint.py) written atomically
    (tmp + ``os.replace``) AFTER the payload, so the manifest is the
    commit point and concurrent replicas racing to publish the same
    entry are safe: both write identical content, last rename wins;
  * the contract is **never-fails**: version mismatch, checksum
    mismatch, a torn write, an unpicklable payload, a backend that
    cannot deserialize — every load-side failure QUARANTINES the entry
    (moved to ``quarantine/``, post-mortem evidence like corrupt
    checkpoints) and returns None, and the caller falls back to a
    fresh compile. A cache can make a restart slow again; it can never
    make it wrong or dead.

Wired callers: ``ServingEngine`` (tick/prefill/spec/probe programs —
warmup collapses to one deserialized-executable probe round per
bucket), ``Trainer`` (the train-step executable: ``step_accounting``'s
AOT compile and the hot-loop step itself dispatch through the cache),
and ``serving/replica_worker.py`` (spec key ``"compile_cache"``) so a
router-respawned replica rejoins in load-bound seconds. Every
hit/miss/store/quarantine is a TelemetryEvent (EVENT_COMPILE_CACHE).

Offline CLI::

    python -m pytorchdistributed_tpu.runtime.compile_cache ls <dir>
    python -m pytorchdistributed_tpu.runtime.compile_cache verify <dir>
    python -m pytorchdistributed_tpu.runtime.compile_cache gc <dir> \
        [--max-age-days D] [--keep N]
    python -m pytorchdistributed_tpu.runtime.compile_cache prewarm <dir> \
        --spec '{"model": "gpt2", "size": "test", ...}'

``prewarm`` compiles + serializes every program a replica-worker spec
would need (all prefill buckets + the tick family) BEFORE deploy, so
the first real worker to start finds a fully warm cache.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import pathlib
import pickle
import time

import jax

from pytorchdistributed_tpu.faults.retry import IO_RETRY, RetryPolicy, retry
from pytorchdistributed_tpu.telemetry.events import (
    EVENT_COMPILE_CACHE,
    EventLog,
)

#: env contract: point every process of a deployment (trainer workers,
#: serving replicas, the router's respawned workers) at one shared
#: cache directory — next to the checkpoint dir is the natural home
COMPILE_CACHE_DIR_ENV = "PTD_COMPILE_CACHE"

QUARANTINE_DIR = "quarantine"

#: process-global outcome counters (hit / miss / store / quarantined /
#: serialize_unsupported / store_failed / exec_failed) — the tests' and
#: the coldstart bench's zero-fresh-compiles tripwire reads these the
#: way serving tests read engine TRACE_COUNTS.
CACHE_STATS: collections.Counter = collections.Counter()


class _CacheEntryError(RuntimeError):
    """Internal: positive evidence an on-disk entry is unusable (version
    drift, checksum mismatch, torn files) — always quarantined, never
    propagated."""


def backend_fingerprint() -> dict:
    """The topology half of the cache key: platform, device kinds and
    counts, process count. A serialized executable embeds device
    assignments, so an entry must never be offered to a different
    backend shape."""
    devices = jax.devices()
    return {
        "platform": jax.default_backend(),
        "device_kinds": sorted({d.device_kind for d in devices}),
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
    }


def _leaf_signature(leaf) -> list:
    shape = list(getattr(leaf, "shape", ()))
    dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
    sharding = getattr(leaf, "sharding", None)
    return [shape, dtype, repr(sharding) if sharding is not None else ""]


def args_signature(example_args) -> dict:
    """Avals + shardings + tree structure of the program's dynamic
    arguments (jax.Arrays or ShapeDtypeStructs both carry all three) —
    the part of the key that pins the executable to its exact calling
    convention."""
    leaves, treedef = jax.tree_util.tree_flatten(example_args)
    return {"treedef": str(treedef),
            "leaves": [_leaf_signature(x) for x in leaves]}


def static_repr(value) -> str:
    """Stable string for a static argument. Flax modules hash by
    identity, which is useless across processes — their config
    dataclass repr is the portable identity (two clones with equal
    configs lower to the same program)."""
    cfg = getattr(value, "cfg", None)
    if cfg is not None:
        return f"{type(value).__name__}({cfg!r})"
    return repr(value)


class CompileCache:
    """One persistent executable-cache directory.

    ``load_or_compile(name, compile_fn, example_args, ...)`` is the
    whole integration surface: compute the key, try to deserialize a
    committed entry (any failure quarantines it and falls through),
    otherwise run ``compile_fn()`` (the caller's ``lower().compile()``
    thunk) and publish the result. The returned ``jax.stages.Compiled``
    is called with the program's DYNAMIC arguments only (statics are
    baked into the executable).
    """

    def __init__(self, directory, *, rank: int | None = None,
                 events: EventLog | None | str = "auto",
                 retry_policy: RetryPolicy = IO_RETRY):
        self.directory = pathlib.Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        if rank is None:
            rank = int(os.environ.get("RANK", "0"))
        self.rank = rank
        self._events = (EventLog.from_env(rank) if events == "auto"
                        else events)
        self._retry_policy = retry_policy

    # -- construction helpers ------------------------------------------

    @classmethod
    def from_env(cls) -> "CompileCache | None":
        """The PTD_COMPILE_CACHE contract (None when unset) — how
        launched workers opt in without code changes."""
        d = os.environ.get(COMPILE_CACHE_DIR_ENV)
        return cls(d) if d else None

    @classmethod
    def resolve(cls, value) -> "CompileCache | None":
        """Normalize a user-facing knob: an instance passes through,
        "auto" reads the env contract, None/""/"off" disables, a path
        opens that directory."""
        if value is None or value == "" or value == "off":
            return None
        if isinstance(value, cls):
            return value
        if value == "auto":
            return cls.from_env()
        return cls(value)

    # -- keys ----------------------------------------------------------

    def entry_key(self, name: str, example_args, *, statics: str = "",
                  config_hash: str = "",
                  donation: str = "") -> tuple[dict, str]:
        """(key components, sha256 digest). Everything that could
        invalidate a serialized executable is IN the key, so staleness
        can only ever manifest as a miss."""
        import jaxlib

        key = {
            "v": 1,
            "name": name,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": backend_fingerprint(),
            "statics": statics,
            "config": config_hash,
            "donation": donation,
            "args": args_signature(example_args),
        }
        digest = hashlib.sha256(
            json.dumps(key, sort_keys=True).encode()).hexdigest()
        return key, digest

    def _paths(self, digest: str) -> tuple[pathlib.Path, pathlib.Path]:
        return (self.directory / f"{digest}.bin",
                self.directory / f"{digest}.json")

    # -- load ----------------------------------------------------------

    def load(self, name: str, example_args, *, statics: str = "",
             config_hash: str = "", donation: str = ""):
        key, digest = self.entry_key(name, example_args, statics=statics,
                                     config_hash=config_hash,
                                     donation=donation)
        return self._load(name, digest)

    def _load(self, name: str, digest: str):
        """Deserialize a committed entry; None on miss OR on any
        defect (which also quarantines the entry) — the never-fails
        half of the contract."""
        bin_path, man_path = self._paths(digest)
        if not man_path.exists():
            return None
        try:
            meta = json.loads(retry(man_path.read_text,
                                    policy=self._retry_policy,
                                    describe=f"compile_cache manifest "
                                             f"{digest[:12]}",
                                    events=self._events))
            self._check_meta(meta)
            if not bin_path.exists():
                raise _CacheEntryError("manifest without payload (torn "
                                       "publish)")
            data = retry(bin_path.read_bytes, policy=self._retry_policy,
                         describe=f"compile_cache payload {digest[:12]}",
                         events=self._events)
            if len(data) != meta.get("size"):
                raise _CacheEntryError(
                    f"payload size {len(data)} != manifest "
                    f"{meta.get('size')}")
            if hashlib.sha256(data).hexdigest() != meta.get("sha256"):
                raise _CacheEntryError("payload checksum mismatch")
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = pickle.loads(data)
            compiled = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — the never-fails contract
            self.quarantine(digest, reason=f"{type(e).__name__}: {e}")
            return None
        CACHE_STATS["hit"] += 1
        self._event("hit", name=name, digest=digest[:12])
        return compiled

    def _check_meta(self, meta: dict) -> None:
        """Belt-and-braces version/backend gate: the digest already
        encodes all of this, so a mismatch here means the entry was
        tampered with or the key scheme drifted — either way it must
        not load."""
        import jaxlib

        fp = backend_fingerprint()
        for field, want in (("jax", jax.__version__),
                            ("jaxlib", jaxlib.__version__),
                            ("platform", fp["platform"])):
            have = meta.get(field)
            if have != want:
                raise _CacheEntryError(
                    f"{field} mismatch: entry has {have!r}, runtime is "
                    f"{want!r}")

    # -- store ---------------------------------------------------------

    def store(self, name: str, key: dict, digest: str, compiled) -> bool:
        """Serialize + publish atomically. Payload first, manifest
        (the commit point) second; both via unique-tmp + os.replace, so
        racing replicas publishing the same digest both succeed. Never
        raises — a backend that cannot serialize costs a telemetry
        event, not the job."""
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            data = pickle.dumps((payload, in_tree, out_tree))
        except Exception as e:  # noqa: BLE001 — the never-fails contract
            CACHE_STATS["serialize_unsupported"] += 1
            self._event("serialize_unsupported", name=name,
                        digest=digest[:12],
                        error=f"{type(e).__name__}: {e}"[:200])
            return False
        import jaxlib

        bin_path, man_path = self._paths(digest)
        meta = {
            "name": name,
            "digest": digest,
            "sha256": hashlib.sha256(data).hexdigest(),
            "size": len(data),
            "created": round(time.time(), 3),
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": key["backend"]["platform"],
            "key": key,
        }
        # unique per WRITER, not per pid: two threads of one process
        # (or pid-coinciding hosts on a shared filesystem) racing the
        # same digest must never share a tmp path, or truncate-write-
        # rename atomicity — the whole publish contract — is gone
        import uuid

        nonce = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
        try:
            tmp = bin_path.with_name(f"{bin_path.name}.tmp{nonce}")
            tmp.write_bytes(data)
            os.replace(tmp, bin_path)
            tmp = man_path.with_name(f"{man_path.name}.tmp{nonce}")
            tmp.write_text(json.dumps(meta, indent=0, sort_keys=True))
            os.replace(tmp, man_path)
        except OSError as e:
            CACHE_STATS["store_failed"] += 1
            self._event("store_failed", name=name, digest=digest[:12],
                        error=f"{type(e).__name__}: {e}"[:200])
            return False
        CACHE_STATS["store"] += 1
        self._event("store", name=name, digest=digest[:12],
                    bytes=len(data))
        return True

    # -- the integration surface ---------------------------------------

    def load_or_compile(self, name: str, compile_fn, example_args, *,
                        statics: str = "", config_hash: str = "",
                        donation: str = ""):
        """Returns ``(jax.stages.Compiled, "hit" | "miss")``. A hit
        deserializes (no trace, no XLA compile); a miss runs
        ``compile_fn()`` — the caller's ``lower().compile()`` thunk,
        whose errors propagate since the jit path would fail
        identically — and publishes the result for the next process."""
        key, digest = self.entry_key(name, example_args, statics=statics,
                                     config_hash=config_hash,
                                     donation=donation)
        compiled = self._load(name, digest)
        if compiled is not None:
            return compiled, "hit"
        CACHE_STATS["miss"] += 1
        self._event("miss", name=name, digest=digest[:12])
        compiled = compile_fn()
        self.store(name, key, digest, compiled)
        return compiled, "miss"

    def note_exec_failure(self, name: str, error: BaseException) -> None:
        """A deserialized executable failed at CALL time (e.g. a
        sharding-committed argument the baked convention rejects): the
        caller dropped it and fell back to jit — record why."""
        CACHE_STATS["exec_failed"] += 1
        self._event("exec_failed", name=name,
                    error=f"{type(error).__name__}: {error}"[:200])

    # -- quarantine / maintenance --------------------------------------

    def quarantine(self, digest: str, *, reason: str = "") -> None:
        """Move a defective entry out of the lookup path (evidence,
        not garbage — same philosophy as checkpoint quarantine).
        Race-tolerant: losing the os.replace to a sibling process is
        success."""
        qdir = self.directory / QUARANTINE_DIR
        qdir.mkdir(exist_ok=True)
        for path in self._paths(digest):
            if not path.exists():
                continue
            dest = qdir / path.name
            if dest.exists():
                dest = qdir / f"{path.name}.{int(time.time() * 1e3)}"
            try:
                os.replace(path, dest)
            except FileNotFoundError:
                pass  # a sibling process quarantined it first
        CACHE_STATS["quarantined"] += 1
        self._event("quarantine", digest=digest[:12], reason=reason[:200])

    def entries(self) -> list[dict]:
        """Manifest metadata of every committed entry (newest first)."""
        out = []
        for man in sorted(self.directory.glob("*.json")):
            try:
                out.append(json.loads(man.read_text()))
            except (OSError, ValueError):
                continue  # torn manifest: verify/gc handle it
        return sorted(out, key=lambda m: m.get("created", 0),
                      reverse=True)

    def verify(self) -> list[tuple[str, bool, str]]:
        """Offline integrity sweep: (digest, ok, detail) per entry —
        checksum and version checks only, nothing is loaded onto
        devices and nothing is quarantined (the CLI reports; the load
        path enforces)."""
        out = []
        seen = set()
        for man in sorted(self.directory.glob("*.json")):
            digest = man.stem
            seen.add(digest)
            bin_path = self.directory / f"{digest}.bin"
            try:
                meta = json.loads(man.read_text())
                self._check_meta(meta)
                data = bin_path.read_bytes()
                if len(data) != meta.get("size"):
                    raise _CacheEntryError("size mismatch")
                if hashlib.sha256(data).hexdigest() != meta.get("sha256"):
                    raise _CacheEntryError("checksum mismatch")
            except Exception as e:  # noqa: BLE001 — report, don't raise
                out.append((digest, False, f"{type(e).__name__}: {e}"))
                continue
            out.append((digest, True,
                        f"{meta.get('name', '?')} {meta.get('size', 0)}B"))
        for orphan in sorted(self.directory.glob("*.bin")):
            if orphan.stem not in seen:
                out.append((orphan.stem, False,
                            "payload without manifest (torn publish)"))
        return out

    def gc(self, *, max_age_days: float | None = None,
           keep: int | None = None) -> int:
        """Delete entries older than ``max_age_days`` and/or beyond the
        ``keep`` newest; payload-without-manifest orphans always go.
        Returns the number of entries removed."""
        removed = 0
        entries = self.entries()
        cutoff = (time.time() - max_age_days * 86400.0
                  if max_age_days is not None else None)
        for i, meta in enumerate(entries):
            dead = ((cutoff is not None
                     and meta.get("created", 0) < cutoff)
                    or (keep is not None and i >= keep))
            if not dead:
                continue
            for path in self._paths(meta["digest"]):
                try:
                    path.unlink()
                except OSError:
                    pass
            removed += 1
        manifests = {m.stem for m in self.directory.glob("*.json")}
        for orphan in self.directory.glob("*.bin"):
            if orphan.stem not in manifests:
                try:
                    orphan.unlink()
                    removed += 1
                except OSError:
                    pass
        return removed

    # -- internals -----------------------------------------------------

    def _event(self, action: str, **data) -> None:
        if self._events is not None:
            self._events.emit(EVENT_COMPILE_CACHE, step=-1, action=action,
                              **data)


def stats_snapshot() -> dict:
    """Plain-dict copy of CACHE_STATS (the tests/bench tripwire)."""
    return dict(CACHE_STATS)


# ---------------------------------------------------------------------
# offline CLI


def _cmd_ls(cache: CompileCache) -> int:
    entries = cache.entries()
    if not entries:
        print(f"no entries under {cache.directory}")
        return 0
    print(f"{'digest':<14}{'name':<28}{'bytes':>12}  {'platform':<8}"
          f"{'jax':<10}created")
    for m in entries:
        created = time.strftime("%Y-%m-%d %H:%M:%S",
                                time.localtime(m.get("created", 0)))
        print(f"{m.get('digest', '?')[:12]:<14}"
              f"{m.get('name', '?')[:26]:<28}{m.get('size', 0):>12}  "
              f"{m.get('platform', '?'):<8}{m.get('jax', '?'):<10}"
              f"{created}")
    print(f"{len(entries)} entr{'y' if len(entries) == 1 else 'ies'}")
    return 0


def _cmd_verify(cache: CompileCache) -> int:
    verdicts = cache.verify()
    if not verdicts:
        print(f"no entries under {cache.directory}")
        return 0
    bad = 0
    for digest, ok, detail in verdicts:
        print(f"{digest[:12]:<14}{'OK' if ok else 'CORRUPT':<9}{detail}")
        bad += not ok
    print(f"{len(verdicts)} entr{'y' if len(verdicts) == 1 else 'ies'}, "
          f"{bad} bad")
    return 1 if bad else 0


def _cmd_prewarm(cache_dir: str, spec_json: str) -> int:
    """Compile + serialize every program a replica-worker spec needs —
    the deploy-time half of seconds-scale replica join. Reuses the
    worker's own engine builder so prewarmed programs are exactly the
    ones a live worker will ask for."""
    spec = json.loads(spec_json)
    spec.setdefault("engine", {})["compile_cache"] = cache_dir
    # the canonical module's counters, NOT this file's globals: under
    # ``python -m`` runpy executes a second copy of this file as
    # __main__, while the engine increments the normally-imported one
    from pytorchdistributed_tpu.runtime.compile_cache import (
        stats_snapshot as canonical_stats,
    )
    from pytorchdistributed_tpu.serving.replica_worker import _build_engine

    before = canonical_stats()
    engine = _build_engine(spec)
    engine.warmup(prompt_lens=spec.get("warmup_lens") or None)
    engine.close()
    after = canonical_stats()
    delta = {k: after.get(k, 0) - before.get(k, 0)
             for k in set(after) | set(before)}
    print(json.dumps({"prewarmed": delta.get("store", 0),
                      "already_cached": delta.get("hit", 0),
                      "serialize_unsupported":
                          delta.get("serialize_unsupported", 0)}))
    return 0


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        "pytorchdistributed_tpu.runtime.compile_cache")
    sub = parser.add_subparsers(dest="cmd", required=True)
    for name, doc in (("ls", "list committed entries"),
                      ("verify", "integrity-check every entry"),
                      ("gc", "delete old/excess entries")):
        p = sub.add_parser(name, help=doc)
        p.add_argument("directory")
    sub.choices["gc"].add_argument("--max-age-days", type=float,
                                   default=None)
    sub.choices["gc"].add_argument("--keep", type=int, default=None)
    pw = sub.add_parser(
        "prewarm", help="compile + serialize every program a replica "
                        "spec needs (deploy-time warm cache)")
    pw.add_argument("directory")
    pw.add_argument("--spec", required=True,
                    help="replica_worker JSON spec (model/size/engine "
                         "kwargs; optional warmup_lens)")
    args = parser.parse_args(argv)
    if args.cmd == "prewarm":
        return _cmd_prewarm(args.directory, args.spec)
    cache = CompileCache(args.directory, events=None)
    if args.cmd == "ls":
        return _cmd_ls(cache)
    if args.cmd == "verify":
        return _cmd_verify(cache)
    removed = cache.gc(max_age_days=args.max_age_days, keep=args.keep)
    print(f"removed {removed} entr{'y' if removed == 1 else 'ies'}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
