"""Process-group lifecycle — the TPU-native `init_process_group`.

The reference initializes distributed training two ways (SURVEY.md §3):

  * spawn-style: explicit ``rank``/``world_size`` args plus
    ``MASTER_ADDR``/``MASTER_PORT`` env (reference ddp_gpus.py:11-23), and
  * torchrun-style: everything from the env contract
    ``RANK/WORLD_SIZE/LOCAL_RANK/MASTER_ADDR/MASTER_PORT``
    (reference ddp_gpus_torchrun.py:11-19).

On TPU there is no userspace collective library to boot: rendezvous is
`jax.distributed.initialize` (coordinator address + process id), after which
XLA collectives over ICI/DCN just work. This module supports both reference
entry styles on top of that, resolving, in priority order:

  1. explicit arguments,
  2. the torchrun env contract (so launch scripts port unchanged),
  3. JAX/TPU automatic slice-metadata discovery (args all None on a pod).

Single-process runs (one host, 1..N local devices — including CPU simulation)
skip `jax.distributed.initialize` entirely, mirroring how the reference's CPU
"gloo smoke" config needs no NCCL.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax


@dataclass
class _ProcessGroupState:
    # rank/world_size are intentionally NOT cached here: jax.process_index()
    # / jax.process_count() are the single source of truth after init.
    initialized: bool = False
    multiprocess: bool = False
    local_rank: int = 0


_state = _ProcessGroupState()


def _env_int(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def init_process_group(
    *,
    coordinator_address: str | None = None,
    world_size: int | None = None,
    rank: int | None = None,
    local_device_ids: list[int] | None = None,
) -> None:
    """Initialize the distributed runtime (idempotent).

    Mirrors the contract of the reference's ``ddp_setup``
    (ddp_gpus.py:11-23 / ddp_gpus_torchrun.py:11-19): explicit args win,
    otherwise the torchrun env contract, otherwise TPU auto-discovery.
    """
    if _state.initialized:
        return

    # torchrun-style env contract (reference ddp_gpus_torchrun.py:14-19).
    # NB: rank 0 is falsy — only a None env lookup may fall through.
    if rank is None:
        rank = _env_int("RANK")
    if rank is None:
        rank = _env_int("PROCESS_ID")
    if world_size is None:
        world_size = _env_int("WORLD_SIZE")
    if world_size is None:
        world_size = _env_int("NUM_PROCESSES")
    if coordinator_address is None:
        addr = os.environ.get("MASTER_ADDR") or os.environ.get(
            "COORDINATOR_ADDRESS"
        )
        if addr:
            port = os.environ.get("MASTER_PORT", "12355")
            coordinator_address = addr if ":" in addr else f"{addr}:{port}"

    multiprocess = (world_size or 1) > 1 or coordinator_address is not None
    if multiprocess:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=world_size,
            process_id=rank,
            local_device_ids=local_device_ids,
        )
    local_rank = _env_int("LOCAL_RANK")
    _state.local_rank = 0 if local_rank is None else local_rank
    _state.multiprocess = multiprocess
    _state.initialized = True


def destroy_process_group() -> None:
    """Tear down the runtime (reference ddp_gpus.py:83)."""
    if _state.multiprocess:
        jax.distributed.shutdown()
    _state.initialized = False
    _state.multiprocess = False


def is_initialized() -> bool:
    return _state.initialized


def get_rank() -> int:
    """Process rank (the reference's ``rank``, 02_ddp.ipynb cell 1)."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of processes (the reference's ``world_size``)."""
    return jax.process_count()


def get_local_rank() -> int:
    return _state.local_rank


def is_main_process() -> bool:
    """True on the rank responsible for logging/checkpoint metadata (the
    reference prints from every rank — SURVEY.md §5 flags that as a wart)."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
