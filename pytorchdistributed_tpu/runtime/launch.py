"""Process launching — the framework's L2 (SURVEY.md §1).

Two entry styles, mirroring the reference's lesson pair:

  * ``launch(fn, nprocs)`` — the `mp.spawn` style (reference ddp_gpus.py:98):
    parent spawns one process per "device group", passing the rank as the
    first argument;
  * ``python -m pytorchdistributed_tpu.run --nproc-per-node N script.py``
    — the torchrun style (reference ddp_gpus_torchrun.py:102): an agent
    process sets the env contract (RANK / WORLD_SIZE / LOCAL_RANK /
    MASTER_ADDR / MASTER_PORT) and the script reads it via
    runtime.dist.init_process_group. Implemented in runtime/run.py with
    elastic restart (SURVEY.md §5 "Failure detection").

On a real TPU pod there is one process per host and the TPU runtime itself
provides topology metadata, so these launchers matter for (a) CPU-sim
multi-process testing — the analog of BASELINE's "gloo CPU smoke" — and
(b) driving jax.distributed rendezvous when infra (GKE/QueuedResources)
doesn't.
"""

from __future__ import annotations

import multiprocessing
import os
import re
import socket
import time
from typing import Callable, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def sim_device_flags(inherited: str, devices_per_proc: int) -> str:
    """XLA_FLAGS for a CPU-sim worker: strip any pre-existing
    device-count flag first (e.g. from a test/CI env), so the result holds
    exactly one — relying on XLA's last-flag-wins is brittle."""
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   inherited)
    return (f"{flags} --xla_force_host_platform_device_count="
            f"{devices_per_proc}").strip()


def _worker_env(rank: int, world_size: int, port: int,
                devices_per_proc: int | None) -> dict[str, str]:
    env = {
        "RANK": str(rank),
        "LOCAL_RANK": str(rank),
        "WORLD_SIZE": str(world_size),
        "MASTER_ADDR": "localhost",
        "MASTER_PORT": str(port),
    }
    if devices_per_proc is not None:
        # CPU-sim: each process gets its own simulated chips
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = sim_device_flags(
            os.environ.get("XLA_FLAGS", ""), devices_per_proc)
    return env


def _worker(fn: Callable, rank: int, world_size: int, port: int,
            devices_per_proc: int | None, args: tuple) -> None:
    os.environ.update(_worker_env(rank, world_size, port, devices_per_proc))
    fn(rank, *args)


def launch(
    fn: Callable,
    nprocs: int,
    *,
    args: Sequence = (),
    devices_per_proc: int | None = None,
    timeout: float | None = None,
) -> None:
    """Spawn ``nprocs`` processes running ``fn(rank, *args)`` with the
    rendezvous env set (the reference's ``mp.spawn(main, args=...,
    nprocs=world_size)``, ddp_gpus.py:98). Raises RuntimeError if any child
    exits nonzero — after terminating the rest (fail-fast, the behavior
    torchrun's agent provides)."""
    ctx = multiprocessing.get_context("spawn")
    port = _free_port()
    procs = [
        ctx.Process(
            target=_worker,
            args=(fn, rank, nprocs, port, devices_per_proc, tuple(args)),
            name=f"tpu-dist-rank{rank}",
        )
        for rank in range(nprocs)
    ]
    for p in procs:
        p.start()
    # Poll ALL children (like run.py's agent) rather than join()ing them in
    # order: a sequential join can hang forever when a later rank crashes
    # while an earlier one blocks in a collective waiting for it.
    deadline = None if timeout is None else time.monotonic() + timeout
    failed = None
    try:
        while failed is None:
            codes = {rank: p.exitcode for rank, p in enumerate(procs)}
            for rank, code in codes.items():
                if code not in (None, 0):
                    failed = (rank, f"exit code {code}")
                    break
            else:
                if all(c == 0 for c in codes.values()):
                    return
                if deadline is not None and time.monotonic() > deadline:
                    rank = next(r for r, c in codes.items() if c is None)
                    failed = (rank, "timeout")
                    break
                time.sleep(0.05)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(10)
    raise RuntimeError(
        f"rank {failed[0]} failed ({failed[1]}); terminated the rest")
