"""Process launching — the framework's L2 (SURVEY.md §1).

Two entry styles, mirroring the reference's lesson pair:

  * ``launch(fn, nprocs)`` — the `mp.spawn` style (reference ddp_gpus.py:98):
    parent spawns one process per "device group", passing the rank as the
    first argument;
  * ``python -m pytorchdistributed_tpu.run --nproc-per-node N script.py``
    — the torchrun style (reference ddp_gpus_torchrun.py:102): an agent
    process sets the env contract (RANK / WORLD_SIZE / LOCAL_RANK /
    MASTER_ADDR / MASTER_PORT) and the script reads it via
    runtime.dist.init_process_group. Implemented in runtime/run.py with
    elastic restart (SURVEY.md §5 "Failure detection").

On a real TPU pod there is one process per host and the TPU runtime itself
provides topology metadata, so these launchers matter for (a) CPU-sim
multi-process testing — the analog of BASELINE's "gloo CPU smoke" — and
(b) driving jax.distributed rendezvous when infra (GKE/QueuedResources)
doesn't.
"""

from __future__ import annotations

import multiprocessing
import os
import socket
from typing import Callable, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env(rank: int, world_size: int, port: int,
                devices_per_proc: int | None) -> dict[str, str]:
    env = {
        "RANK": str(rank),
        "LOCAL_RANK": str(rank),
        "WORLD_SIZE": str(world_size),
        "MASTER_ADDR": "localhost",
        "MASTER_PORT": str(port),
    }
    if devices_per_proc is not None:
        # CPU-sim: each process gets its own simulated chips
        env["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{devices_per_proc}").strip()
    return env


def _worker(fn: Callable, rank: int, world_size: int, port: int,
            devices_per_proc: int | None, args: tuple) -> None:
    os.environ.update(_worker_env(rank, world_size, port, devices_per_proc))
    fn(rank, *args)


def launch(
    fn: Callable,
    nprocs: int,
    *,
    args: Sequence = (),
    devices_per_proc: int | None = None,
    timeout: float | None = None,
) -> None:
    """Spawn ``nprocs`` processes running ``fn(rank, *args)`` with the
    rendezvous env set (the reference's ``mp.spawn(main, args=...,
    nprocs=world_size)``, ddp_gpus.py:98). Raises RuntimeError if any child
    exits nonzero — after terminating the rest (fail-fast, the behavior
    torchrun's agent provides)."""
    ctx = multiprocessing.get_context("spawn")
    port = _free_port()
    procs = [
        ctx.Process(
            target=_worker,
            args=(fn, rank, nprocs, port, devices_per_proc, tuple(args)),
            name=f"tpu-dist-rank{rank}",
        )
        for rank in range(nprocs)
    ]
    for p in procs:
        p.start()
    failed = None
    try:
        for rank, p in enumerate(procs):
            p.join(timeout)
            if p.exitcode is None:
                failed = failed or (rank, "timeout")
            elif p.exitcode != 0:
                failed = failed or (rank, f"exit code {p.exitcode}")
    finally:
        if failed:
            for p in procs:
                if p.is_alive():
                    p.terminate()
    if failed:
        raise RuntimeError(
            f"rank {failed[0]} failed ({failed[1]}); terminated the rest")
