"""Per-rank liveness heartbeats for hung-rank detection (SURVEY.md §5
"Failure detection": the reference *uses* torchrun's elastic agent,
ddp_gpus_torchrun.py:102, but a rank wedged in a collective — the NCCL
deadlock analog — never *exits*, so exit-watching alone hangs the group
forever).

Contract: the launcher (`pytorchdistributed_tpu.run --heartbeat-timeout T`)
exports ``PTD_HEARTBEAT_DIR``; each worker touches ``rank<RANK>`` in it
whenever it proves forward progress, and the agent kills + relaunches the
group when any rank's file goes stale for more than T seconds.

What counts as progress: a beat must follow a *device sync* (reading a
metric value back), not merely host-loop progress — JAX dispatch is async,
so a host can happily loop enqueueing steps while the devices sit
deadlocked in a collective. The Trainer beats exactly where it blocks on
device values (the log-cadence metrics read), so choose
``T >> log_every × step_time``.
"""

from __future__ import annotations

import contextlib
import os
import threading
from pathlib import Path

HEARTBEAT_DIR_ENV = "PTD_HEARTBEAT_DIR"


class Heartbeat:
    """Touches this rank's liveness file; cheap enough to call in the hot
    loop (an utime syscall, no device work)."""

    def __init__(self, path: str | os.PathLike):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # Deliberately NO beat here: the first beat must mark real
        # progress. Stamping the file at construction would start the
        # agent's `timeout` clock before the first XLA compile (minutes on
        # big models) — the launcher's more generous `grace` window covers
        # a rank until it has genuinely beaten once.

    @classmethod
    def from_env(cls) -> "Heartbeat | None":
        """The worker-side hook: a Heartbeat when the launcher asked for
        one (PTD_HEARTBEAT_DIR set), else None."""
        d = os.environ.get(HEARTBEAT_DIR_ENV)
        if not d:
            return None
        return cls(Path(d) / f"rank{os.environ.get('RANK', '0')}")

    def beat(self) -> None:
        try:
            os.utime(self.path)
        except FileNotFoundError:
            self.path.touch()

    @contextlib.contextmanager
    def keepalive(self, interval: float = 1.0):
        """Background beats while a long blocking host operation runs.

        The graceful-preemption path blocks on ``CheckpointManager.
        wait()`` — potentially far longer than the agent's heartbeat
        timeout — and a rank draining its final durable save must not be
        re-classified as hung and killed mid-write. A daemon thread
        touches the liveness file every ``interval`` seconds until the
        block ends; beats from a thread are honest here because the
        wrapped operation is host I/O progress, not the async-dispatch
        illusion the device-sync rule guards against."""
        stop = threading.Event()

        def loop() -> None:
            while not stop.wait(interval):
                self.beat()

        self.beat()
        t = threading.Thread(target=loop, name="ptd-heartbeat-keepalive",
                             daemon=True)
        t.start()
        try:
            yield self
        finally:
            stop.set()
            t.join(timeout=interval + 1.0)


def last_beat_age(path: str | os.PathLike, *,
                  now: float | None = None) -> float | None:
    """Seconds since the liveness file at ``path`` was last touched,
    or None when it has never beaten. The single-file complement of
    ``stale_ranks`` for callers that watch ONE worker (the serving
    replica router surfaces this per subprocess replica next to its
    protocol-level progress watermark)."""
    import time

    try:
        last = Path(path).stat().st_mtime
    except OSError:
        return None
    return max(0.0, (now if now is not None else time.time()) - last)


def stale_ranks(directory: str | os.PathLike, nproc: int, *, timeout: float,
                grace: float, now: float, baseline: float) -> list[int]:
    """Agent-side check: ranks in [0, nproc) whose last beat is older than
    ``timeout`` seconds. A rank that has never beaten (no file yet) is
    judged against ``grace`` from ``baseline`` (the group spawn time)
    instead: imports and the first XLA compile legitimately take tens of
    seconds before any beat, but a worker wedged *before* its first beat is
    still eventually caught. The launcher uses a fresh directory per
    incarnation so a relaunch never inherits the dead group's mtimes."""
    directory = Path(directory)
    stale = []
    for rank in range(nproc):
        try:
            last = (directory / f"rank{rank}").stat().st_mtime
        except OSError:
            if now - baseline > max(grace, timeout):
                stale.append(rank)
            continue
        if now - max(last, baseline) > timeout:
            stale.append(rank)
    return stale
