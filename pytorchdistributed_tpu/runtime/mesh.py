"""Device-mesh construction — the TPU-native replacement for process groups.

The reference reaches parallelism by wrapping models per-strategy
(`DDP(model, device_ids=[gpu_id])`, reference ddp_gpus.py:35; manual two-stage
placement, 03_model_parallel.ipynb cell 5). On TPU the idiomatic equivalent is
ONE `jax.sharding.Mesh` whose named axes encode every strategy at once:

    axis        strategy                      collective traffic
    ----        --------                      ------------------
    "data"      DDP-style data parallelism    grad psum (ICI, or DCN across slices)
    "fsdp"      ZeRO-3 param/opt sharding     all-gather / reduce-scatter (ICI)
    "tensor"    Megatron tensor parallelism   activation psum (fastest ICI axis)
    "pipe"      pipeline stages               ppermute stage boundaries
    "seq"       sequence/context parallelism  ppermute (ring attention) / all_to_all

Axis ordering matters on hardware: `mesh_utils.create_device_mesh` lays axes
onto the ICI torus so the *last* axes get the tightest physical neighborhoods.
We therefore order (data, fsdp, pipe, seq, tensor) — tensor parallelism is the
most latency-sensitive, data parallelism tolerates DCN. For multi-slice pods,
`create_hybrid_device_mesh` pins the "data" axis to DCN (SURVEY.md §5
"Distributed communication backend").
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Axis:
    """Canonical mesh-axis names used across the framework."""

    DATA = "data"
    FSDP = "fsdp"
    TENSOR = "tensor"
    PIPE = "pipe"
    SEQ = "seq"
    EXPERT = "expert"

    # Order = DCN-most-tolerant first, ICI-latency-hungriest last.
    ALL = (DATA, FSDP, EXPERT, PIPE, SEQ, TENSOR)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis. ``-1`` on exactly one axis means
    "absorb all remaining devices" (like the reference's
    ``world_size = torch.cuda.device_count()``, ddp_gpus.py:94).
    """

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    seq: int = 1
    tensor: int = 1
    # Number of pod slices connected over DCN. >1 selects the hybrid
    # (ICI x DCN) mesh; the "data" axis then spans DCN.
    num_slices: int = 1

    def sizes(self) -> dict[str, int]:
        return {
            Axis.DATA: self.data,
            Axis.FSDP: self.fsdp,
            Axis.EXPERT: self.expert,
            Axis.PIPE: self.pipe,
            Axis.SEQ: self.seq,
            Axis.TENSOR: self.tensor,
        }

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Resolve -1 entries against the device count; validate the product."""
        sizes = self.sizes()
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one axis may be -1, got {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = n_devices // known
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {total} devices but {n_devices} are available"
            )
        return sizes


def create_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[Any] | None = None,
    **axis_sizes: int,
) -> Mesh:
    """Build the framework's device mesh.

    ``create_mesh()`` → all devices on the "data" axis (pure DDP).
    ``create_mesh(tensor=4)`` → remaining devices on "data", 4-way TP.
    ``create_mesh(MeshConfig(num_slices=2, fsdp=8))`` → hybrid DCN mesh.
    """
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    elif axis_sizes:
        config = dataclasses.replace(config, **axis_sizes)

    if devices is None:
        devices = jax.devices()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in Axis.ALL)

    if config.num_slices > 1:
        device_array = hybrid_device_array(config.num_slices, shape, devices)
    else:
        try:
            device_array = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True
            )
        except (ValueError, NotImplementedError):
            # CPU-sim / host platforms without topology info: plain reshape.
            device_array = np.asarray(devices).reshape(shape)

    return Mesh(device_array, Axis.ALL)


def hybrid_device_array(num_slices: int, shape: tuple,
                        devices: Sequence[Any]) -> np.ndarray:
    """Device layout for a multi-slice (ICI x DCN) pod: the "data" axis
    (axis 0 of ``shape``) spans slices — each slice's devices fill a
    contiguous block of data rows, so every other axis's collectives stay on
    intra-slice ICI and only data-parallel gradient reduction crosses DCN
    (SURVEY.md §5 "Distributed communication backend").

    Uses `mesh_utils.create_hybrid_device_mesh` on real TPU topologies and
    falls back to a slice-major reshape when devices carry no topology
    (CPU sim, fake test devices): sorted by (slice_index, id), slice k
    occupies data rows [k*D/S, (k+1)*D/S).
    """
    data_total = shape[0]
    if data_total % num_slices != 0:
        raise ValueError(
            f"data axis {data_total} must be a multiple of "
            f"num_slices {num_slices}")
    per_slice = list(shape)
    per_slice[0] = data_total // num_slices
    dcn = [1] * len(shape)
    dcn[0] = num_slices
    try:
        return mesh_utils.create_hybrid_device_mesh(
            per_slice, dcn, devices=devices, allow_split_physical_axes=True
        )
    except (ValueError, AttributeError, NotImplementedError):
        devs = sorted(devices,
                      key=lambda d: (getattr(d, "slice_index", 0),
                                     getattr(d, "id", 0)))
        return np.asarray(devs, dtype=object).reshape(shape)


def local_mesh(n: int | None = None) -> Mesh:
    """Mesh over this process's addressable devices only (single-host runs,
    CPU simulation via --xla_force_host_platform_device_count)."""
    devices = jax.local_devices()
    if n is not None:
        devices = devices[:n]
    return create_mesh(MeshConfig(), devices=devices)


def mesh_shape(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *, seq_axis: bool = False) -> NamedSharding:
    """Sharding for a [batch, ...] array: batch split over every
    data-parallel-like axis (data+fsdp), optionally sequence dim over "seq"."""
    batch_axes = tuple(
        a for a in (Axis.DATA, Axis.FSDP) if mesh.shape[a] > 1
    ) or (Axis.DATA,)
    if seq_axis and mesh.shape[Axis.SEQ] > 1:
        return NamedSharding(mesh, P(batch_axes, Axis.SEQ))
    return NamedSharding(mesh, P(batch_axes))


def batch_leaf_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Rank-aware batch sharding: leading dim over the data axes; 2-D
    token-shaped leaves ([batch, seq] tokens/targets/masks) additionally
    sharded over "seq" when the mesh has a context-parallel axis. Rank-1
    leaves (labels) and rank-4 images never get a seq spec."""
    return batch_sharding(mesh, seq_axis=(ndim == 2))


def data_parallel_size(mesh: Mesh) -> int:
    return mesh.shape[Axis.DATA] * mesh.shape[Axis.FSDP]
