from pytorchdistributed_tpu.runtime.mesh import (  # noqa: F401
    Axis,
    MeshConfig,
    create_mesh,
    local_mesh,
)
from pytorchdistributed_tpu.runtime.dist import (  # noqa: F401
    init_process_group,
    destroy_process_group,
    get_rank,
    get_world_size,
    is_initialized,
)
from pytorchdistributed_tpu.runtime.compile_cache import (  # noqa: F401
    COMPILE_CACHE_DIR_ENV,
    CompileCache,
)
